"""Actor frontend: ActorClass / ActorHandle / method calls.

Reference: python/ray/actor.py — ActorClass (:544), its _remote (:830),
ActorHandle (:1193), ActorMethod wrappers.
"""

from __future__ import annotations

import functools
from typing import Any

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.actor_runtime import exit_actor  # re-export  # noqa: F401
from ray_tpu._private.ids import ActorID
from ray_tpu._private.task import normalize_resources
from ray_tpu.remote_function import _VALID_OPTIONS, _build_strategy

_ACTOR_OPTIONS = _VALID_OPTIONS | {
    "max_concurrency", "max_restarts", "max_task_retries", "max_pending_calls",
    "lifetime", "namespace", "get_if_exists", "process",
}


class ActorMethod:
    """Bound remote method: ``handle.method.remote(...)``."""

    def __init__(self, actor_id: ActorID, method_name: str,
                 num_returns: int = 1,
                 deadline_s: "float | None" = None):
        self._actor_id = actor_id
        self._method_name = method_name
        self._num_returns = num_returns
        # Per-call end-to-end budget default (the actor's
        # ``_deadline_s`` option); .options(_deadline_s=...) overrides.
        self._deadline_s = deadline_s

    def options(self, **opts) -> "ActorMethod":
        method = ActorMethod(self._actor_id, self._method_name,
                             opts.get("num_returns", self._num_returns),
                             opts.get("_deadline_s", self._deadline_s))
        return method

    def remote(self, *args, **kwargs):
        runtime = worker_mod.auto_init()
        refs = runtime.submit_actor_task(
            self._actor_id, self._method_name, args, kwargs,
            num_returns=self._num_returns,
            deadline_s=self._deadline_s)
        if self._num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node over a live actor (reference: ray.dag
        ClassMethodNode)."""
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            "use '.remote()'.")


class ActorHandle:
    """A serializable handle to a live actor (reference: actor.py:1193)."""

    def __init__(self, actor_id: ActorID, class_name: str = "Actor"):
        self._actor_id = actor_id
        self._class_name = class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        num_returns = 1
        deadline_s = None
        runtime = worker_mod.global_runtime()
        if runtime is not None:
            record = runtime.gcs.get_actor(self._actor_id)
            if record is not None:
                num_returns = record.method_meta.get(name, {}).get("num_returns", 1)
                deadline_s = record.default_deadline_s or None
        return ActorMethod(self._actor_id, name, num_returns, deadline_s)

    def _actor_record(self):
        runtime = worker_mod.auto_init()
        return runtime.gcs.get_actor(self._actor_id)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"


class _ForeignActorMethod:
    """Bound method of an actor owned by ANOTHER driver; calls route to
    the owner's client server (reference: cross-driver named actors via
    the GCS actor table, gcs_actor_manager.h)."""

    def __init__(self, handle: "ForeignActorHandle", method_name: str):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = 1

    def options(self, *, num_returns: int = 1) -> "_ForeignActorMethod":
        method = _ForeignActorMethod(self._handle, self._method_name)
        method._num_returns = num_returns
        return method

    def remote(self, *args, **kwargs):
        runtime = worker_mod.auto_init()
        refs = runtime.submit_foreign_actor_task(
            self._handle._owner_addr, self._handle._actor_key,
            self._method_name, args, kwargs,
            num_returns=self._num_returns)
        if self._num_returns == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called "
            "directly; use '.remote()'.")


class ForeignActorHandle:
    """Handle to a named actor living in another driver's runtime,
    resolved through the cluster actor directory (GCS KV)."""

    def __init__(self, owner_addr: str, actor_key: str,
                 class_name: str = "Actor",
                 method_meta: dict | None = None):
        self._owner_addr = owner_addr
        self._actor_key = actor_key
        self._class_name = class_name
        self._method_meta = dict(method_meta or {})

    def __getattr__(self, name: str) -> _ForeignActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        method = _ForeignActorMethod(self, name)
        method._num_returns = self._method_meta.get(name, {}).get(
            "num_returns", 1)
        return method

    def __reduce__(self):
        return (ForeignActorHandle,
                (self._owner_addr, self._actor_key, self._class_name,
                 self._method_meta))

    def __hash__(self):
        return hash((self._owner_addr, self._actor_key))

    def __eq__(self, other):
        return (isinstance(other, ForeignActorHandle)
                and other._owner_addr == self._owner_addr
                and other._actor_key == self._actor_key)

    def __repr__(self):
        return (f"ForeignActorHandle({self._class_name}, "
                f"{self._actor_key[:12]}@{self._owner_addr})")


class ActorClass:
    """A class turned into an actor factory via ``@ray_tpu.remote``."""

    def __init__(self, cls: type, default_options: dict | None = None):
        self._cls = cls
        self._default_options = dict(default_options or {})
        bad = set(self._default_options) - _ACTOR_OPTIONS
        if bad:
            raise ValueError(f"Invalid actor options: {sorted(bad)}")
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            "directly. Use '.remote()' to create an actor, or access the "
            "underlying class via '.cls'.")

    @property
    def cls(self) -> type:
        return self._cls

    def options(self, **options) -> "ActorClass":
        bad = set(options) - _ACTOR_OPTIONS
        if bad:
            raise ValueError(f"Invalid options: {sorted(bad)}")
        return ActorClass(self._cls, {**self._default_options, **options})

    def remote(self, *args, **kwargs) -> ActorHandle:
        runtime = worker_mod.auto_init()
        opts = self._default_options
        resources = normalize_resources(
            opts.get("num_cpus"),
            opts.get("num_tpus") or opts.get("num_gpus"),
            opts.get("resources"),
            default_cpus=0.0,  # actors default to 0 CPU like the reference
        )
        actor_id, creation_ref = runtime.create_actor(
            self._cls, args, kwargs,
            name=opts.get("name"),
            namespace=opts.get("namespace"),
            resources=resources,
            max_concurrency=opts.get("max_concurrency", 1),
            max_restarts=opts.get("max_restarts", 0),
            max_pending_calls=opts.get("max_pending_calls", -1),
            lifetime=opts.get("lifetime"),
            scheduling_strategy=_build_strategy(opts),
            get_if_exists=opts.get("get_if_exists", False),
            process=opts.get("process", False),
            runtime_env=opts.get("runtime_env"),
            deadline_s=opts.get("_deadline_s"),
        )
        handle = ActorHandle(actor_id, self._cls.__name__)
        handle._creation_ref = creation_ref  # keeps creation error observable
        return handle

    def __repr__(self):
        return f"ActorClass({self._cls.__name__})"


def method(num_returns: int = 1):
    """Decorator carrying per-method defaults (reference: ray.method)."""

    def decorator(fn):
        fn.__ray_tpu_num_returns__ = num_returns
        return fn

    return decorator
