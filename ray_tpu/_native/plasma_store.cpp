// plasma_store.cpp — shared-memory arena object store (plasma-lite).
//
// TPU-native analogue of the reference's plasma store
// (src/ray/object_manager/plasma/store_runner.h, object_store.h,
// plasma_allocator.cc): ONE shared-memory arena mapped by every process,
// with an in-arena allocator and object table, instead of one POSIX
// segment per object (segment-per-object costs shm_open+ftruncate+mmap
// per object; the arena costs one lock round-trip per object).
//
// Layout:   [Header | ObjectEntry table | heap]
// All cross-process references are OFFSETS from the arena base (each
// process maps the arena at a different address).
//
// Concurrency: one process-shared ROBUST pthread mutex in the header.
// Robustness matters: a pool worker can be SIGKILLed while holding the
// lock; EOWNERDEAD lets the next locker recover instead of deadlocking
// (the reference store is single-process and serializes via its event
// loop; here clients mutate the arena directly, so the lock must
// survive client death).
//
// Eviction: sealed objects with refcount 0 are evictable, oldest
// lru_tick first — the same "evict only sealed, unused, LRU" policy as
// plasma's eviction_policy.cc.
//
// Build: g++ -O2 -shared -fPIC plasma_store.cpp -o libray_tpu_native.so -lpthread -lrt

#include <cstdint>
#include <cstring>
#include <cerrno>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52415954505541ULL;  // "RAYTPUA"
constexpr uint32_t kVersion = 1;
constexpr uint64_t kAlign = 64;
constexpr int kIdSize = 16;

// Object states.
enum : int32_t {
  kEmpty = 0,       // table slot unused
  kCreated = 1,     // allocated, being written (not visible to get)
  kSealed = 2,      // immutable, visible
  kTombstone = 3,   // deleted slot (probe chains continue past it)
};

struct ObjectEntry {
  uint8_t id[kIdSize];
  uint64_t offset;     // payload offset from arena base
  uint64_t size;       // payload size
  int32_t state;
  int32_t refcount;
  uint64_t lru_tick;
  int32_t creator_pid; // for reclaiming kCreated leaks of dead writers
  int32_t pad_;
};

// Free block header, stored inside the heap at the block's offset.
// Free list is singly linked, sorted by offset, so freeing can merge
// adjacent blocks in one pass.
struct FreeBlock {
  uint64_t size;       // block size including this header
  uint64_t next;       // offset of next free block (0 = end)
};

struct Header {
  uint64_t magic;
  uint32_t version;
  uint32_t table_capacity;   // power of two
  uint64_t arena_size;
  uint64_t table_offset;
  uint64_t heap_offset;
  uint64_t heap_size;
  uint64_t free_head;        // offset of first free block (0 = none)
  uint64_t used_bytes;       // payload bytes in live objects
  uint64_t num_objects;      // created + sealed
  uint64_t lru_clock;
  uint64_t num_evictions;
  uint64_t alloc_failures;
  pthread_mutex_t lock;
};

struct Handle {
  uint8_t* base;
  uint64_t mapped_size;
  bool owner;
};

inline Header* header(Handle* h) {
  return reinterpret_cast<Header*>(h->base);
}

inline ObjectEntry* table(Handle* h) {
  return reinterpret_cast<ObjectEntry*>(h->base + header(h)->table_offset);
}

inline uint64_t align_up(uint64_t v, uint64_t a) {
  return (v + a - 1) & ~(a - 1);
}

// FNV-1a over the 16-byte id.
inline uint64_t hash_id(const uint8_t* id) {
  uint64_t x = 1469598103934665603ULL;
  for (int i = 0; i < kIdSize; i++) {
    x ^= id[i];
    x *= 1099511628211ULL;
  }
  return x;
}

void rebuild_free_list(Handle* h);

// Lock with EOWNERDEAD recovery: a client died mid-operation, so the
// free list may be torn (half-written splice). The object table is
// authoritative (entries are committed with a single state write), so
// recovery rebuilds the free list from the live entries; at worst the
// dead client's in-flight allocation leaks as a kCreated entry, which
// the dead-writer reclaim in evict_lru later frees.
int lock_arena(Handle* h) {
  Header* hd = header(h);
  int rc = pthread_mutex_lock(&hd->lock);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&hd->lock);
    rebuild_free_list(h);
    return 0;
  }
  return rc;
}

void unlock_arena(Header* hd) { pthread_mutex_unlock(&hd->lock); }

// Find the table slot for id (nullptr if absent). Caller holds lock.
ObjectEntry* find_entry(Handle* h, const uint8_t* id) {
  Header* hd = header(h);
  ObjectEntry* tab = table(h);
  uint32_t mask = hd->table_capacity - 1;
  uint32_t slot = static_cast<uint32_t>(hash_id(id)) & mask;
  for (uint32_t probe = 0; probe <= mask; probe++, slot = (slot + 1) & mask) {
    ObjectEntry* e = &tab[slot];
    if (e->state == kEmpty) return nullptr;
    if (e->state != kTombstone && memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return nullptr;
}

// Find a slot to insert id into (nullptr if table full). Caller holds lock.
ObjectEntry* insert_slot(Handle* h, const uint8_t* id) {
  Header* hd = header(h);
  ObjectEntry* tab = table(h);
  uint32_t mask = hd->table_capacity - 1;
  uint32_t slot = static_cast<uint32_t>(hash_id(id)) & mask;
  ObjectEntry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe <= mask; probe++, slot = (slot + 1) & mask) {
    ObjectEntry* e = &tab[slot];
    if (e->state == kEmpty) return first_tomb ? first_tomb : e;
    if (e->state == kTombstone) {
      if (!first_tomb) first_tomb = e;
    } else if (memcmp(e->id, id, kIdSize) == 0) {
      return nullptr;  // duplicate id
    }
  }
  return first_tomb;  // table full of live entries -> nullptr
}

// First-fit allocation from the sorted free list. Caller holds lock.
// Returns payload offset, or 0 on failure.
uint64_t heap_alloc(Handle* h, uint64_t payload) {
  Header* hd = header(h);
  uint64_t need = align_up(payload < sizeof(FreeBlock) ? sizeof(FreeBlock)
                                                       : payload, kAlign);
  uint64_t prev = 0;
  uint64_t cur = hd->free_head;
  while (cur) {
    FreeBlock* b = reinterpret_cast<FreeBlock*>(h->base + cur);
    if (b->size >= need) {
      uint64_t rest = b->size - need;
      if (rest >= align_up(sizeof(FreeBlock), kAlign)) {
        // Split: tail remains free.
        uint64_t tail_off = cur + need;
        FreeBlock* tail = reinterpret_cast<FreeBlock*>(h->base + tail_off);
        tail->size = rest;
        tail->next = b->next;
        if (prev) reinterpret_cast<FreeBlock*>(h->base + prev)->next = tail_off;
        else hd->free_head = tail_off;
      } else {
        need = b->size;  // absorb the remainder
        if (prev) reinterpret_cast<FreeBlock*>(h->base + prev)->next = b->next;
        else hd->free_head = b->next;
      }
      return cur;
    }
    prev = cur;
    cur = b->next;
  }
  return 0;
}

// Free a block: insert into the offset-sorted free list and coalesce
// with adjacent free blocks. Caller holds lock.
void heap_free(Handle* h, uint64_t off, uint64_t payload) {
  Header* hd = header(h);
  uint64_t size = align_up(payload < sizeof(FreeBlock) ? sizeof(FreeBlock)
                                                       : payload, kAlign);
  uint64_t prev = 0;
  uint64_t cur = hd->free_head;
  while (cur && cur < off) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(h->base + cur)->next;
  }
  FreeBlock* blk = reinterpret_cast<FreeBlock*>(h->base + off);
  blk->size = size;
  blk->next = cur;
  if (prev) reinterpret_cast<FreeBlock*>(h->base + prev)->next = off;
  else hd->free_head = off;
  // Merge with next.
  if (cur && off + blk->size == cur) {
    FreeBlock* nxt = reinterpret_cast<FreeBlock*>(h->base + cur);
    blk->size += nxt->size;
    blk->next = nxt->next;
  }
  // Merge with prev.
  if (prev) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(h->base + prev);
    if (prev + pb->size == off) {
      pb->size += blk->size;
      pb->next = blk->next;
    }
  }
}

// Remove an entry: tombstone it, then — if no probe chain continues
// past this slot (next slot empty) — convert it and any contiguous
// preceding tombstones back to kEmpty so lookup misses stay O(chain)
// instead of degrading to O(table) as tombstones accumulate.
void remove_entry(Handle* h, ObjectEntry* e) {
  Header* hd = header(h);
  ObjectEntry* tab = table(h);
  uint32_t mask = hd->table_capacity - 1;
  e->state = kTombstone;
  uint32_t idx = static_cast<uint32_t>(e - tab);
  if (tab[(idx + 1) & mask].state != kEmpty) return;
  uint32_t i = idx;
  do {
    if (tab[i].state != kTombstone) return;
    tab[i].state = kEmpty;
    i = (i - 1) & mask;
  } while (i != idx);
}

void evict_one(Handle* h, ObjectEntry* victim) {
  Header* hd = header(h);
  heap_free(h, victim->offset, victim->size);
  hd->used_bytes -= victim->size;
  hd->num_objects--;
  hd->num_evictions++;
  remove_entry(h, victim);
}

// Rebuild the free list from the object table (EOWNERDEAD recovery: the
// list links may be torn, but entries are committed with a single state
// store, so live offsets/sizes are trustworthy). O(n^2) selection over
// live entries — recovery-only, not a hot path.
void rebuild_free_list(Handle* h) {
  Header* hd = header(h);
  ObjectEntry* tab = table(h);
  hd->free_head = 0;
  uint64_t cursor = hd->heap_offset;
  uint64_t heap_end = hd->heap_offset + (hd->heap_size & ~(kAlign - 1));
  uint64_t tail = 0;  // last free block appended
  for (;;) {
    // Find the live block with the smallest offset >= cursor.
    ObjectEntry* next_live = nullptr;
    for (uint32_t i = 0; i < hd->table_capacity; i++) {
      ObjectEntry* e = &tab[i];
      if ((e->state == kCreated || e->state == kSealed) &&
          e->offset >= cursor &&
          (!next_live || e->offset < next_live->offset)) {
        next_live = e;
      }
    }
    uint64_t gap_end = next_live ? next_live->offset : heap_end;
    if (gap_end > cursor) {
      uint64_t off = cursor;
      FreeBlock* blk = reinterpret_cast<FreeBlock*>(h->base + off);
      blk->size = gap_end - cursor;
      blk->next = 0;
      if (tail) reinterpret_cast<FreeBlock*>(h->base + tail)->next = off;
      else hd->free_head = off;
      tail = off;
    }
    if (!next_live) return;
    uint64_t sz = next_live->size < sizeof(FreeBlock) ? sizeof(FreeBlock)
                                                      : next_live->size;
    cursor = next_live->offset + align_up(sz, kAlign);
  }
}

// Evict until at least `need` heap bytes could plausibly be satisfied.
// Policy (plasma's eviction_policy.cc, plus dead-writer reclaim):
//   1. sealed refcount-0 objects, oldest lru_tick first;
//   2. kCreated leftovers whose creator process no longer exists
//      (writer crashed between create and seal).
// Victims are gathered in batches of up to 64 per O(table) scan so a
// large reclaim is O(table * ceil(victims/64)), not O(table * victims),
// all under the arena lock. Caller holds lock. Returns true if
// anything was evicted.
bool evict_lru(Handle* h, uint64_t need) {
  Header* hd = header(h);
  ObjectEntry* tab = table(h);
  bool any = false;
  constexpr int kBatch = 64;
  for (;;) {
    // Gather up to kBatch oldest evictable entries in one scan
    // (insertion sort into a small local buffer, newest-evicted-last).
    ObjectEntry* batch[kBatch];
    int n = 0;
    for (uint32_t i = 0; i < hd->table_capacity; i++) {
      ObjectEntry* e = &tab[i];
      bool evictable =
          (e->state == kSealed && e->refcount == 0) ||
          (e->state == kCreated && e->creator_pid > 0 &&
           kill(e->creator_pid, 0) != 0 && errno == ESRCH);
      if (!evictable) continue;
      int j = n < kBatch ? n : kBatch - 1;
      if (j == kBatch - 1 && n == kBatch &&
          e->lru_tick >= batch[j]->lru_tick) {
        continue;  // older than everything buffered
      }
      while (j > 0 && batch[j - 1]->lru_tick > e->lru_tick) {
        batch[j] = batch[j - 1];
        j--;
      }
      batch[j] = e;
      if (n < kBatch) n++;
    }
    if (n == 0) return any;
    for (int i = 0; i < n; i++) {
      evict_one(h, batch[i]);
      any = true;
      uint64_t probe = heap_alloc(h, need);
      if (probe) {
        heap_free(h, probe, need);
        return true;
      }
    }
  }
}

}  // namespace

extern "C" {

// Create + initialize an arena. Returns handle or nullptr.
void* rt_store_create(const char* name, uint64_t arena_size,
                      uint32_t table_capacity) {
  // Round table capacity up to a power of two.
  uint32_t cap = 64;
  while (cap < table_capacity) cap <<= 1;

  shm_unlink(name);  // stale arena from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(arena_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, arena_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }

  Header* hd = reinterpret_cast<Header*>(base);
  memset(hd, 0, sizeof(Header));
  hd->magic = kMagic;
  hd->version = kVersion;
  hd->table_capacity = cap;
  hd->arena_size = arena_size;
  hd->table_offset = align_up(sizeof(Header), kAlign);
  uint64_t table_bytes = align_up(cap * sizeof(ObjectEntry), kAlign);
  hd->heap_offset = hd->table_offset + table_bytes;
  if (hd->heap_offset + kAlign >= arena_size) {
    munmap(base, arena_size);
    shm_unlink(name);
    return nullptr;
  }
  hd->heap_size = arena_size - hd->heap_offset;
  memset(reinterpret_cast<uint8_t*>(base) + hd->table_offset, 0, table_bytes);

  // Heap starts as one big free block.
  FreeBlock* first = reinterpret_cast<FreeBlock*>(
      reinterpret_cast<uint8_t*>(base) + hd->heap_offset);
  first->size = hd->heap_size & ~(kAlign - 1);
  first->next = 0;
  hd->free_head = hd->heap_offset;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hd->lock, &attr);
  pthread_mutexattr_destroy(&attr);

  Handle* h = new Handle{reinterpret_cast<uint8_t*>(base), arena_size, true};
  return h;
}

// Attach to an existing arena. Returns handle or nullptr.
void* rt_store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Header* hd = reinterpret_cast<Header*>(base);
  if (hd->magic != kMagic || hd->version != kVersion) {
    munmap(base, st.st_size);
    return nullptr;
  }
  Handle* h = new Handle{reinterpret_cast<uint8_t*>(base),
                         static_cast<uint64_t>(st.st_size), false};
  return h;
}

void rt_store_detach(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  munmap(h->base, h->mapped_size);
  delete h;
}

int rt_store_destroy(void* hv, const char* name) {
  Handle* h = static_cast<Handle*>(hv);
  munmap(h->base, h->mapped_size);
  delete h;
  return shm_unlink(name);
}

uint8_t* rt_store_base(void* hv) {
  return static_cast<Handle*>(hv)->base;
}

// Allocate an object. Returns payload offset, 0 on failure (full).
uint64_t rt_store_create_object(void* hv, const uint8_t* id, uint64_t size) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return 0;
  ObjectEntry* e = insert_slot(h, id);
  if (!e) {
    // insert_slot fails for BOTH a full table and a duplicate id; a
    // duplicate must fail cleanly (never evict the live same-id object
    // or unrelated entries). Only a genuinely full table earns an
    // eviction pass: tombstoned entries free slots, then retry.
    if (find_entry(h, id) != nullptr || !evict_lru(h, size) ||
        !(e = insert_slot(h, id))) {
      hd->alloc_failures++;
      unlock_arena(hd);
      return 0;
    }
  }
  uint64_t off = heap_alloc(h, size);
  if (!off) {
    if (evict_lru(h, size)) off = heap_alloc(h, size);
    if (!off) {
      hd->alloc_failures++;
      unlock_arena(hd);
      return 0;
    }
    // Eviction turned slots into tombstones; our insert slot may have
    // been re-usable anyway, but re-find to be safe.
    e = insert_slot(h, id);
    if (!e) {
      heap_free(h, off, size);
      hd->alloc_failures++;
      unlock_arena(hd);
      return 0;
    }
  }
  memcpy(e->id, id, kIdSize);
  e->offset = off;
  e->size = size;
  e->state = kCreated;
  e->refcount = 0;
  e->lru_tick = ++hd->lru_clock;
  e->creator_pid = static_cast<int32_t>(getpid());
  hd->used_bytes += size;
  hd->num_objects++;
  unlock_arena(hd);
  return off;
}

// Seal: make the object visible to get(). Returns 0 ok, -1 not found.
int rt_store_seal(void* hv, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return -1;
  ObjectEntry* e = find_entry(h, id);
  if (!e || e->state != kCreated) {
    unlock_arena(hd);
    return -1;
  }
  e->state = kSealed;
  e->lru_tick = ++hd->lru_clock;
  unlock_arena(hd);
  return 0;
}

// Seal + take a reference in one critical section: the object is never
// observable in the sealed-refcount-0 (evictable) state, so ownership
// hands off to the eventual releaser with no eviction race.
int rt_store_seal_pinned(void* hv, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return -1;
  ObjectEntry* e = find_entry(h, id);
  if (!e || e->state != kCreated) {
    unlock_arena(hd);
    return -1;
  }
  e->state = kSealed;
  e->refcount = 1;
  e->lru_tick = ++hd->lru_clock;
  unlock_arena(hd);
  return 0;
}

// Get: addref + return payload offset (0 if absent/unsealed); size via out.
uint64_t rt_store_get(void* hv, const uint8_t* id, uint64_t* size_out) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return 0;
  ObjectEntry* e = find_entry(h, id);
  if (!e || e->state != kSealed) {
    unlock_arena(hd);
    return 0;
  }
  e->refcount++;
  e->lru_tick = ++hd->lru_clock;
  if (size_out) *size_out = e->size;
  uint64_t off = e->offset;
  unlock_arena(hd);
  return off;
}

// Peek: payload offset + size WITHOUT taking a reference (0 if
// absent/unsealed). For same-host peers mapping another process's
// arena: the peer stays read-only (never mutates refcounts in someone
// else's arena — a crashed peer then cannot leak pins); the OWNER pins
// on the peer's behalf for the lease's life (rt_store_get/release via
// the lease table), which is what keeps the peeked offset valid.
uint64_t rt_store_peek(void* hv, const uint8_t* id, uint64_t* size_out) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return 0;
  ObjectEntry* e = find_entry(h, id);
  if (!e || e->state != kSealed) {
    unlock_arena(hd);
    return 0;
  }
  if (size_out) *size_out = e->size;
  uint64_t off = e->offset;
  unlock_arena(hd);
  return off;
}

// Release a get() reference. Returns 0 ok, -1 not found.
int rt_store_release(void* hv, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return -1;
  ObjectEntry* e = find_entry(h, id);
  if (!e || e->refcount <= 0) {
    unlock_arena(hd);
    return -1;
  }
  e->refcount--;
  unlock_arena(hd);
  return 0;
}

// Delete: free immediately if refcount 0, else mark for eviction (the
// entry stays until refs drain; evict_lru skips refcount>0). Returns 0.
int rt_store_delete(void* hv, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return -1;
  ObjectEntry* e = find_entry(h, id);
  if (!e) {
    unlock_arena(hd);
    return -1;
  }
  if (e->refcount <= 0) {
    heap_free(h, e->offset, e->size);
    hd->used_bytes -= e->size;
    hd->num_objects--;
    remove_entry(h, e);
  } else {
    // Sealed-with-refs: make it eviction-eligible the moment refs
    // drain by aging it to the oldest possible tick.
    e->lru_tick = 0;
  }
  unlock_arena(hd);
  return 0;
}

int rt_store_contains(void* hv, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return 0;
  ObjectEntry* e = find_entry(h, id);
  int ok = (e && e->state == kSealed) ? 1 : 0;
  unlock_arena(hd);
  return ok;
}

void rt_store_stats(void* hv, uint64_t* used, uint64_t* capacity,
                    uint64_t* num_objects, uint64_t* evictions,
                    uint64_t* alloc_failures) {
  Handle* h = static_cast<Handle*>(hv);
  Header* hd = header(h);
  if (lock_arena(h) != 0) return;
  if (used) *used = hd->used_bytes;
  if (capacity) *capacity = hd->heap_size;
  if (num_objects) *num_objects = hd->num_objects;
  if (evictions) *evictions = hd->num_evictions;
  if (alloc_failures) *alloc_failures = hd->alloc_failures;
  unlock_arena(hd);
}

}  // extern "C"
