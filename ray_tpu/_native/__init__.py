"""Native (C++) components, compiled on demand.

The reference ships ~166K LoC of C++ under src/ray/ prebuilt by Bazel;
here the native layer is small enough to build lazily with the system
toolchain the first time it is needed, cached next to the source. If no
toolchain is available the callers fall back to pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = [os.path.join(_DIR, "plasma_store.cpp"),
            os.path.join(_DIR, "node_store.cpp"),
            os.path.join(_DIR, "gcs_kv.cpp")]
_LIB = os.path.join(_DIR, "libray_tpu_native.so")

_lock = threading.Lock()
_lib: "ctypes.CDLL | None | bool" = None  # False = tried and failed


def _build() -> bool:
    cmd = ["g++", "-O2", "-shared", "-fPIC", *_SOURCES, "-o", _LIB,
           "-lpthread", "-lrt"]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0 and os.path.exists(_LIB)


def load() -> "ctypes.CDLL | None":
    """Compile (if stale/missing) and dlopen the native library.

    Returns None when the toolchain or build is unavailable; callers
    must degrade gracefully.
    """
    global _lib
    with _lock:
        if _lib is not None:
            return _lib or None
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < max(
                        os.path.getmtime(s) for s in _SOURCES)):
                if not _build():
                    _lib = False
                    return None
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _lib = False
            return None

        u64, u32, p = ctypes.c_uint64, ctypes.c_uint32, ctypes.c_void_p
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rt_store_create.restype = p
        lib.rt_store_create.argtypes = [ctypes.c_char_p, u64, u32]
        lib.rt_store_attach.restype = p
        lib.rt_store_attach.argtypes = [ctypes.c_char_p]
        lib.rt_store_detach.restype = None
        lib.rt_store_detach.argtypes = [p]
        lib.rt_store_destroy.restype = ctypes.c_int
        lib.rt_store_destroy.argtypes = [p, ctypes.c_char_p]
        lib.rt_store_base.restype = u8p
        lib.rt_store_base.argtypes = [p]
        lib.rt_store_create_object.restype = u64
        lib.rt_store_create_object.argtypes = [p, ctypes.c_char_p, u64]
        lib.rt_store_seal.restype = ctypes.c_int
        lib.rt_store_seal.argtypes = [p, ctypes.c_char_p]
        lib.rt_store_seal_pinned.restype = ctypes.c_int
        lib.rt_store_seal_pinned.argtypes = [p, ctypes.c_char_p]
        lib.rt_store_get.restype = u64
        lib.rt_store_get.argtypes = [p, ctypes.c_char_p,
                                     ctypes.POINTER(u64)]
        lib.rt_store_peek.restype = u64
        lib.rt_store_peek.argtypes = [p, ctypes.c_char_p,
                                      ctypes.POINTER(u64)]
        lib.rt_store_release.restype = ctypes.c_int
        lib.rt_store_release.argtypes = [p, ctypes.c_char_p]
        lib.rt_store_delete.restype = ctypes.c_int
        lib.rt_store_delete.argtypes = [p, ctypes.c_char_p]
        lib.rt_store_contains.restype = ctypes.c_int
        lib.rt_store_contains.argtypes = [p, ctypes.c_char_p]
        lib.rt_store_stats.restype = None
        lib.rt_store_stats.argtypes = [p] + [ctypes.POINTER(u64)] * 5
        # Node object store (node_store.cpp, rt_ns_*).
        i64 = ctypes.c_int64
        lib.rt_ns_create.restype = p
        lib.rt_ns_create.argtypes = [u64, u64, ctypes.c_char_p]
        lib.rt_ns_destroy.restype = None
        lib.rt_ns_destroy.argtypes = [p]
        lib.rt_ns_put.restype = ctypes.c_int
        lib.rt_ns_put.argtypes = [p, ctypes.c_char_p, ctypes.c_char_p,
                                  u64, ctypes.c_int, ctypes.c_char_p]
        lib.rt_ns_read.restype = i64
        lib.rt_ns_read.argtypes = [p, ctypes.c_char_p, u64, u8p, u64,
                                   ctypes.POINTER(u64)]
        lib.rt_ns_size.restype = i64
        lib.rt_ns_size.argtypes = [p, ctypes.c_char_p]
        lib.rt_ns_free.restype = ctypes.c_int
        lib.rt_ns_free.argtypes = [p, ctypes.c_char_p, u32]
        lib.rt_ns_free_owner.restype = ctypes.c_int
        lib.rt_ns_free_owner.argtypes = [p, ctypes.c_char_p]
        lib.rt_ns_owners.restype = i64
        lib.rt_ns_owners.argtypes = [p, ctypes.c_char_p, u64]
        lib.rt_ns_stats.restype = None
        lib.rt_ns_stats.argtypes = [p, ctypes.POINTER(u64)]
        _lib = lib
        return lib
