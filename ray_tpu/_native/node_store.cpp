// Native node object store — the worker daemon's blob store in C++.
//
// Reference: the raylet's local object store + LocalObjectManager
// (src/ray/object_manager/object_store.h, local_object_manager.h:110
// SpillObjects): primary copies of task/actor results keyed by 16-byte
// ids, owner-tagged for owner-death sweeps, spilled to disk past a cap
// and restored on fetch; pulled peer copies in a FIFO-evicted cache.
//
// Python binds via ctypes (rt_ns_* C API, see
// ray_tpu/_private/node_store_native.py). Reads copy into caller
// buffers, so no store memory ever outlives the mutex — and because
// ctypes releases the GIL around calls, concurrent chunk fetches do
// their memcpy/pread without serializing the daemon's Python threads.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <list>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

struct Key {
  uint8_t b[16];
  bool operator==(const Key& o) const { return !memcmp(b, o.b, 16); }
};

struct KeyHash {
  size_t operator()(const Key& k) const {
    uint64_t h;
    memcpy(&h, k.b, 8);
    uint64_t l;
    memcpy(&l, k.b + 8, 8);
    return static_cast<size_t>(h * 1000003ULL ^ l);
  }
};

struct Entry {
  std::string data;        // in-memory bytes (empty once spilled)
  std::string spill_path;  // non-empty => on disk
  uint64_t size = 0;
  bool cached = false;
  std::string owner;
  uint64_t seq = 0;  // insertion order: spill victims are the oldest
};

struct NodeStore {
  std::mutex mu;
  std::unordered_map<Key, Entry, KeyHash> map;
  std::list<Key> cache_order;  // FIFO of cached (pulled) copies
  // seq -> key for IN-MEMORY primaries: spill victims pop from the
  // front in O(log n) instead of rescanning the whole map per victim.
  std::map<uint64_t, Key> primary_order;
  uint64_t cache_bytes = 0;
  uint64_t primary_bytes = 0;
  uint64_t cache_limit = 0;
  uint64_t primary_limit = 0;
  uint64_t fetches = 0;
  uint64_t spills = 0;
  uint64_t restores = 0;
  uint64_t next_seq = 0;
  std::string spill_dir;
};

std::string hex16(const uint8_t* id) {
  static const char* d = "0123456789abcdef";
  std::string out(32, '0');
  for (int i = 0; i < 16; i++) {
    out[2 * i] = d[id[i] >> 4];
    out[2 * i + 1] = d[id[i] & 0xF];
  }
  return out;
}

// mu held. Forget an entry entirely (memory + spill file + owner tag).
bool forget_locked(NodeStore* s, const Key& k) {
  auto it = s->map.find(k);
  if (it == s->map.end()) return false;
  Entry& e = it->second;
  if (!e.spill_path.empty()) {
    unlink(e.spill_path.c_str());
  } else if (e.cached) {
    s->cache_bytes -= e.data.size();
    for (auto c = s->cache_order.begin(); c != s->cache_order.end(); ++c) {
      if (*c == k) { s->cache_order.erase(c); break; }
    }
  } else {
    s->primary_bytes -= e.data.size();
    s->primary_order.erase(e.seq);
  }
  s->map.erase(it);
  return true;
}

// Recursive mkdir (the Python store uses os.makedirs; a nested spill
// dir must not silently disable spilling).
void mkdir_p(const std::string& path) {
  std::string cur;
  for (size_t i = 0; i <= path.size(); i++) {
    if (i == path.size() || path[i] == '/') {
      if (!cur.empty()) mkdir(cur.c_str(), 0777);
      if (i < path.size()) cur += '/';
      continue;
    }
    cur += path[i];
  }
}

// mu held. Spill the oldest in-memory primaries until under the cap.
// The spill WRITE happens under the mutex: daemon-side simplicity over
// concurrency — reads of spilled entries stream outside the lock
// (rt_ns_read).
void maybe_spill_locked(NodeStore* s, const Key& just_put) {
  while (s->primary_bytes > s->primary_limit) {
    // Oldest in-memory primary from the order index (never the blob
    // being put right now).
    auto ord = s->primary_order.begin();
    if (ord != s->primary_order.end() && ord->second == just_put)
      ++ord;
    if (ord == s->primary_order.end()) return;
    Key victim = ord->second;
    Entry& e = s->map[victim];
    mkdir_p(s->spill_dir);
    char path[4096];
    snprintf(path, sizeof(path), "%s/%d-%s-native.blob",
             s->spill_dir.c_str(), (int)getpid(),
             hex16(victim.b).c_str());
    FILE* f = fopen(path, "wb");
    if (f == nullptr) return;  // unwritable disk: keep in memory
    size_t n = fwrite(e.data.data(), 1, e.data.size(), f);
    fclose(f);
    if (n != e.data.size()) {
      unlink(path);
      return;
    }
    s->primary_bytes -= e.data.size();
    s->primary_order.erase(e.seq);
    e.spill_path = path;
    e.data.clear();
    e.data.shrink_to_fit();
    s->spills++;
  }
}

}  // namespace

extern "C" {

void* rt_ns_create(uint64_t cache_limit, uint64_t primary_limit,
                   const char* spill_dir) {
  NodeStore* s = new NodeStore();
  s->cache_limit = cache_limit;
  s->primary_limit = primary_limit;
  s->spill_dir = spill_dir ? spill_dir : "/tmp/ray_tpu_node_spill";
  return s;
}

void rt_ns_destroy(void* h) {
  NodeStore* s = static_cast<NodeStore*>(h);
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (auto& kv : s->map) {
      if (!kv.second.spill_path.empty())
        unlink(kv.second.spill_path.c_str());
    }
  }
  delete s;
}

int rt_ns_put(void* h, const uint8_t* id, const uint8_t* data,
              uint64_t len, int cached, const char* owner) {
  NodeStore* s = static_cast<NodeStore*>(h);
  Key k;
  memcpy(k.b, id, 16);
  std::lock_guard<std::mutex> g(s->mu);
  forget_locked(s, k);  // reseal replaces any prior copy/spill
  Entry e;
  e.data.assign(reinterpret_cast<const char*>(data), len);
  e.size = len;
  e.cached = cached != 0;
  e.seq = s->next_seq++;
  if (owner != nullptr && owner[0] != '\0' && !e.cached) e.owner = owner;
  s->map.emplace(k, std::move(e));
  if (cached) {
    s->cache_order.push_back(k);
    s->cache_bytes += len;
    while (s->cache_bytes > s->cache_limit && !s->cache_order.empty()) {
      Key victim = s->cache_order.front();
      forget_locked(s, victim);  // erases from cache_order too
    }
  } else {
    s->primary_bytes += len;
    s->primary_order[s->map[k].seq] = k;
    maybe_spill_locked(s, k);
  }
  return 0;
}

// Copy [offset, offset+want) into out; returns the TOTAL object size,
// -1 when absent. Spilled entries stream from disk OUTSIDE the store
// mutex (a multi-GB restore from slow disk must not block every
// put/get/free on the node; a concurrent free unlinks the file and the
// read then reports the object absent — correct, it WAS freed).
int64_t rt_ns_read(void* h, const uint8_t* id, uint64_t offset,
                   uint8_t* out, uint64_t want, uint64_t* copied) {
  NodeStore* s = static_cast<NodeStore*>(h);
  Key k;
  memcpy(k.b, id, 16);
  std::string spill_path;
  uint64_t size = 0;
  uint64_t n = 0;
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->map.find(k);
    if (it == s->map.end()) return -1;
    Entry& e = it->second;
    size = e.size;
    if (offset < size) {
      n = size - offset;
      if (n > want) n = want;
    }
    if (e.spill_path.empty()) {
      if (n > 0) memcpy(out, e.data.data() + offset, n);
      s->fetches++;
      if (copied != nullptr) *copied = n;
      return (int64_t)size;
    }
    spill_path = e.spill_path;
  }
  if (n > 0) {
    FILE* f = fopen(spill_path.c_str(), "rb");
    if (f == nullptr) return -1;  // freed concurrently
    if (fseek(f, (long)offset, SEEK_SET) != 0) {
      fclose(f);
      return -1;
    }
    size_t got = fread(out, 1, n, f);
    fclose(f);
    n = got;
  }
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->restores++;
    s->fetches++;
  }
  if (copied != nullptr) *copied = n;
  return (int64_t)size;
}

int64_t rt_ns_size(void* h, const uint8_t* id) {
  NodeStore* s = static_cast<NodeStore*>(h);
  Key k;
  memcpy(k.b, id, 16);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->map.find(k);
  return it == s->map.end() ? -1 : (int64_t)it->second.size;
}

// ids: n contiguous 16-byte keys. Returns how many existed.
int rt_ns_free(void* h, const uint8_t* ids, uint32_t n) {
  NodeStore* s = static_cast<NodeStore*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  int freed = 0;
  for (uint32_t i = 0; i < n; i++) {
    Key k;
    memcpy(k.b, ids + 16 * i, 16);
    if (forget_locked(s, k)) freed++;
  }
  return freed;
}

int rt_ns_free_owner(void* h, const char* owner) {
  NodeStore* s = static_cast<NodeStore*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::vector<Key> victims;
  for (auto& kv : s->map) {
    if (kv.second.owner == owner && owner[0] != '\0')
      victims.push_back(kv.first);
  }
  for (auto& k : victims) forget_locked(s, k);
  return (int)victims.size();
}

// '\n'-joined unique owners into buf; returns the needed byte count
// (call again with a larger buffer if it exceeds buflen).
int64_t rt_ns_owners(void* h, char* buf, uint64_t buflen) {
  NodeStore* s = static_cast<NodeStore*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string joined;
  std::unordered_map<std::string, bool> seen;
  for (auto& kv : s->map) {
    const std::string& o = kv.second.owner;
    if (o.empty() || seen.count(o)) continue;
    seen[o] = true;
    if (!joined.empty()) joined += '\n';
    joined += o;
  }
  if (joined.size() <= buflen && buf != nullptr)
    memcpy(buf, joined.data(), joined.size());
  return (int64_t)joined.size();
}

void rt_ns_stats(void* h, uint64_t* out /* 9 slots */) {
  NodeStore* s = static_cast<NodeStore*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  uint64_t num_blobs = 0, bytes = 0, spilled = 0, spilled_bytes = 0;
  std::unordered_map<std::string, bool> owners;
  for (auto& kv : s->map) {
    const Entry& e = kv.second;
    if (!e.spill_path.empty()) {
      spilled++;
      spilled_bytes += e.size;
    } else {
      num_blobs++;
      bytes += e.data.size();
    }
    if (!e.owner.empty()) owners[e.owner] = true;
  }
  out[0] = num_blobs;
  out[1] = bytes;
  out[2] = s->fetches;
  out[3] = spilled;
  out[4] = spilled_bytes;
  out[5] = s->spills;
  out[6] = s->restores;
  out[7] = owners.size();
  out[8] = s->primary_bytes;
}

}  // extern "C"
