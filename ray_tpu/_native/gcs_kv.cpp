// Native GCS key-value storage engine.
//
// Reference: src/ray/gcs/gcs_kv_manager.h + store_client/ — the GCS's
// internal KV (function exports, named metadata, cluster config) is a
// C++ storage layer; here it is a namespaced hash map with binary
// snapshot/restore for the head's crash persistence. The Python
// control plane keeps only thin ctypes bindings (gcs_kv_native.py).
//
// ABI conventions (shared with node_store.cpp): plain C symbols,
// two-phase reads (call with a buffer; a return value larger than the
// capacity means "grow and retry" — the data is only written when it
// fits), and a single mutex (the GCS KV is control-plane metadata, not
// a data-plane hot path).

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct KvStore {
  std::mutex mu;
  // map (not unordered): snapshot and keys() iterate in a stable
  // order, which keeps persisted images byte-identical for unchanged
  // state.
  std::map<std::string, std::map<std::string, std::string>> spaces;
  uint64_t version = 0;
};

std::string make_key(const uint8_t* k, size_t klen) {
  return std::string(reinterpret_cast<const char*>(k), klen);
}

void put_u32(std::vector<uint8_t>& out, uint32_t v) {
  out.push_back(v & 0xff);
  out.push_back((v >> 8) & 0xff);
  out.push_back((v >> 16) & 0xff);
  out.push_back((v >> 24) & 0xff);
}

bool get_u32(const uint8_t* data, size_t len, size_t& off, uint32_t& v) {
  if (off + 4 > len) return false;
  v = static_cast<uint32_t>(data[off]) |
      (static_cast<uint32_t>(data[off + 1]) << 8) |
      (static_cast<uint32_t>(data[off + 2]) << 16) |
      (static_cast<uint32_t>(data[off + 3]) << 24);
  off += 4;
  return true;
}

void put_blob(std::vector<uint8_t>& out, const std::string& s) {
  put_u32(out, static_cast<uint32_t>(s.size()));
  out.insert(out.end(), s.begin(), s.end());
}

bool get_blob(const uint8_t* data, size_t len, size_t& off,
              std::string& s) {
  uint32_t n;
  if (!get_u32(data, len, off, n)) return false;
  if (off + n > len) return false;
  s.assign(reinterpret_cast<const char*>(data + off), n);
  off += n;
  return true;
}

// Serialize the whole store (or one namespace's keys) into out.
void serialize_all(KvStore* kv, std::vector<uint8_t>& out) {
  uint32_t total = 0;
  for (auto& ns : kv->spaces) total += ns.second.size();
  put_u32(out, total);
  for (auto& ns : kv->spaces) {
    for (auto& entry : ns.second) {
      put_blob(out, ns.first);
      put_blob(out, entry.first);
      put_blob(out, entry.second);
    }
  }
}

}  // namespace

extern "C" {

void* gcs_kv_create() { return new KvStore(); }

void gcs_kv_destroy(void* h) { delete static_cast<KvStore*>(h); }

uint64_t gcs_kv_version(void* h) {
  KvStore* kv = static_cast<KvStore*>(h);
  std::lock_guard<std::mutex> g(kv->mu);
  return kv->version;
}

// 1 = stored, 0 = key existed and overwrite was 0, -1 = key/value too
// large for the u32-length-prefixed snapshot format (a silently
// truncated prefix would corrupt persisted images).
int gcs_kv_put(void* h, const char* ns, const uint8_t* k, size_t klen,
               const uint8_t* v, size_t vlen, int overwrite) {
  if (klen >= UINT32_MAX || vlen >= UINT32_MAX) return -1;
  KvStore* kv = static_cast<KvStore*>(h);
  std::lock_guard<std::mutex> g(kv->mu);
  auto& space = kv->spaces[ns];
  std::string key = make_key(k, klen);
  if (!overwrite && space.count(key)) return 0;
  space[key] = std::string(reinterpret_cast<const char*>(v), vlen);
  kv->version++;
  return 1;
}

// Value length, -1 if missing. Writes the value only when it fits cap.
long gcs_kv_get(void* h, const char* ns, const uint8_t* k, size_t klen,
                uint8_t* out, size_t cap) {
  KvStore* kv = static_cast<KvStore*>(h);
  std::lock_guard<std::mutex> g(kv->mu);
  auto space = kv->spaces.find(ns);
  if (space == kv->spaces.end()) return -1;
  auto it = space->second.find(make_key(k, klen));
  if (it == space->second.end()) return -1;
  if (it->second.size() <= cap && out != nullptr) {
    std::memcpy(out, it->second.data(), it->second.size());
  }
  return static_cast<long>(it->second.size());
}

int gcs_kv_del(void* h, const char* ns, const uint8_t* k, size_t klen) {
  KvStore* kv = static_cast<KvStore*>(h);
  std::lock_guard<std::mutex> g(kv->mu);
  auto space = kv->spaces.find(ns);
  if (space == kv->spaces.end()) return 0;
  size_t erased = space->second.erase(make_key(k, klen));
  if (erased) kv->version++;
  return erased ? 1 : 0;
}

int gcs_kv_exists(void* h, const char* ns, const uint8_t* k,
                  size_t klen) {
  KvStore* kv = static_cast<KvStore*>(h);
  std::lock_guard<std::mutex> g(kv->mu);
  auto space = kv->spaces.find(ns);
  if (space == kv->spaces.end()) return 0;
  return space->second.count(make_key(k, klen)) ? 1 : 0;
}

// Keys with prefix, serialized [u32 count][u32 len, key]...; returns
// needed size (write happens only when it fits cap).
long gcs_kv_keys(void* h, const char* ns, const uint8_t* prefix,
                 size_t plen, uint8_t* out, size_t cap) {
  KvStore* kv = static_cast<KvStore*>(h);
  std::lock_guard<std::mutex> g(kv->mu);
  std::vector<uint8_t> buf;
  std::string pref = make_key(prefix, plen);
  uint32_t count = 0;
  put_u32(buf, 0);  // patched below
  auto space = kv->spaces.find(ns);
  if (space != kv->spaces.end()) {
    for (auto& entry : space->second) {
      if (entry.first.compare(0, pref.size(), pref) == 0) {
        put_blob(buf, entry.first);
        count++;
      }
    }
  }
  buf[0] = count & 0xff;
  buf[1] = (count >> 8) & 0xff;
  buf[2] = (count >> 16) & 0xff;
  buf[3] = (count >> 24) & 0xff;
  if (buf.size() <= cap && out != nullptr) {
    std::memcpy(out, buf.data(), buf.size());
  }
  return static_cast<long>(buf.size());
}

// Full-image snapshot: [u32 count][ns, key, value]... (blobs are
// u32-length-prefixed). Returns needed size; writes only when it fits.
long gcs_kv_snapshot(void* h, uint8_t* out, size_t cap) {
  KvStore* kv = static_cast<KvStore*>(h);
  std::lock_guard<std::mutex> g(kv->mu);
  std::vector<uint8_t> buf;
  serialize_all(kv, buf);
  if (buf.size() <= cap && out != nullptr) {
    std::memcpy(out, buf.data(), buf.size());
  }
  return static_cast<long>(buf.size());
}

// Merge a snapshot image into the store (restore-on-start semantics:
// existing keys are overwritten). Returns entries applied, -1 on a
// corrupt image (nothing applied).
long gcs_kv_restore(void* h, const uint8_t* data, size_t len) {
  KvStore* kv = static_cast<KvStore*>(h);
  // Parse FIRST, apply after: a truncated image must not half-apply.
  size_t off = 0;
  uint32_t count;
  if (!get_u32(data, len, off, count)) return -1;
  // A forged count must fail cleanly, not bad_alloc on reserve: every
  // entry needs at least 3 length prefixes (12 bytes).
  if (count > (len - off) / 12) return -1;
  std::vector<std::pair<std::string, std::pair<std::string, std::string>>>
      entries;
  entries.reserve(count);
  for (uint32_t i = 0; i < count; i++) {
    std::string ns, key, value;
    if (!get_blob(data, len, off, ns) ||
        !get_blob(data, len, off, key) ||
        !get_blob(data, len, off, value)) {
      return -1;
    }
    entries.emplace_back(std::move(ns),
                         std::make_pair(std::move(key), std::move(value)));
  }
  std::lock_guard<std::mutex> g(kv->mu);
  for (auto& e : entries) {
    kv->spaces[e.first][e.second.first] = e.second.second;
  }
  kv->version++;
  return static_cast<long>(entries.size());
}

}  // extern "C"
