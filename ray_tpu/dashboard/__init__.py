"""ray_tpu.dashboard — HTTP dashboard over the state API.

Reference: dashboard/head.py (DashboardHead serving the web UI +
/api endpoints backed by the GCS). Here one stdlib ThreadingHTTPServer
serves:

- ``/``               minimal auto-refreshing HTML overview
- ``/api/cluster``    resources + node summary
- ``/api/nodes|actors|tasks|objects|placement_groups|jobs``
                      the state-API listings as JSON

Two hosts embed it: a driver runtime (``init(dashboard_port=...)``)
and the head daemon (jobs come from the head's JobManager).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: monospace; margin: 2em; }}
 table {{ border-collapse: collapse; margin-bottom: 2em; }}
 td, th {{ border: 1px solid #999; padding: 4px 10px; text-align: left; }}
 h2 {{ margin-bottom: 0.3em; }}
</style></head><body>
<h1>ray_tpu dashboard</h1>
{sections}
</body></html>"""


def _table(title: str, rows: list[dict], cols: list[str]) -> str:
    import html

    head = "".join(f"<th>{html.escape(c)}</th>" for c in cols)
    body = "".join(
        "<tr>" + "".join(
            f"<td>{html.escape(str(row.get(c, '')))}</td>"
            for c in cols) + "</tr>"
        for row in rows)
    return (f"<h2>{html.escape(title)} ({len(rows)})</h2>"
            f"<table><tr>{head}</tr>{body}</table>")


class Dashboard:
    """Serves snapshots produced by a provider callable so the same
    server works over a live Runtime or a head GcsServer."""

    def __init__(self, provider: Callable[[str], list | dict | None],
                 host: str = "127.0.0.1", port: int = 0):
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                truncated = False
                total = None
                try:
                    if self.path in ("/", "/index.html"):
                        payload = dashboard._render_html().encode()
                        ctype = "text/html"
                    elif self.path.startswith("/api/"):
                        section = self.path[len("/api/"):].strip("/")
                        data = provider(section)
                        if data is None:
                            self.send_error(404, f"unknown: {section}")
                            return
                        # State listings know when limit= dropped rows
                        # (util.state.ListResult); surface it as a
                        # header so API consumers never mistake a
                        # capped listing for the whole table.
                        truncated = bool(getattr(data, "truncated",
                                                 False))
                        total = getattr(data, "total", None)
                        payload = json.dumps(data, default=str).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as exc:  # noqa: BLE001
                    self.send_error(500, str(exc))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                if truncated:
                    self.send_header("X-Ray-Tpu-Truncated", "true")
                    if total is not None:
                        self.send_header("X-Ray-Tpu-Total", str(total))
                self.end_headers()
                self.wfile.write(payload)

        self._provider = provider
        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_port
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dashboard")

    def start(self) -> "Dashboard":
        self._thread.start()
        return self

    def _render_html(self) -> str:
        import html

        sections = []
        cluster = self._provider("cluster") or {}
        sections.append(
            "<h2>cluster</h2><table>" + "".join(
                f"<tr><th>{html.escape(str(k))}</th>"
                f"<td>{html.escape(str(v))}</td></tr>"
                for k, v in cluster.items()) + "</table>")
        for name, cols in (
                ("nodes", ["node_id", "alive", "resources", "labels"]),
                ("node_stats", ["node_id", "address", "pid",
                                "tasks_executed", "running", "actors",
                                "store_blobs", "store_bytes",
                                "spilled_blobs", "native_store",
                                "error"]),
                ("actors", ["actor_id", "class_name", "state", "name"]),
                ("jobs", ["job_id", "status", "entrypoint",
                          "submission_id"]),
                ("tasks", ["task_id", "name", "state"]),
        ):
            rows = self._provider(name)
            if rows:
                sections.append(_table(name, rows[:100], cols))
        return _PAGE.format(sections="".join(sections))

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class NodeStatsCollector:
    """Live per-node stats for the dashboard (reference: the per-node
    dashboard agents + reporter.proto feed node cards — here each
    daemon's executor service IS the node agent, and the dashboard
    polls its ``executor_stats``). Briefly cached so an auto-refreshing
    page doesn't hammer the daemons."""

    def __init__(self, list_nodes: Callable[[], list],
                 cache_s: float = 2.0):
        import time

        self._list_nodes = list_nodes
        self._cache_s = cache_s
        self._time = time.monotonic
        self._lock = threading.Lock()
        self._cached: tuple[float, list] = (-1e9, [])

    @staticmethod
    def _poll_one(node: dict) -> dict:
        from ray_tpu._private.rpc import RpcClient

        row = {"node_id": node.get("node_id", "")[:12],
               "address": node.get("executor_address")}
        try:
            client = RpcClient(row["address"], timeout_s=2.0,
                               connect_timeout_s=1.0)
            try:
                stats = client.call("executor_stats")
            finally:
                client.close()
            store = stats.get("store", {})
            row.update({
                "pid": stats.get("pid"),
                "tasks_executed": stats.get("tasks_executed"),
                "running": stats.get("running"),
                "threads": stats.get("threads"),
                "actors": stats.get("num_actors"),
                "store_blobs": store.get("num_blobs"),
                "store_bytes": store.get("bytes"),
                "spilled_blobs": store.get("spilled_blobs", 0),
                "native_store": bool(store.get("native", False)),
            })
        except Exception as exc:  # noqa: BLE001 — node unreachable
            row["error"] = f"unreachable: {type(exc).__name__}"
        return row

    def collect(self) -> list[dict]:
        from concurrent.futures import ThreadPoolExecutor

        with self._lock:
            ts, rows = self._cached
            if self._time() - ts < self._cache_s:
                return rows
        targets = [n for n in self._list_nodes()
                   if n.get("alive") and n.get("executor_address")]
        if targets:
            # Fan out: one slow/unreachable-but-alive node must not
            # stall the whole section (its probe still bounds at ~3s,
            # but the others return in parallel).
            with ThreadPoolExecutor(
                    max_workers=min(8, len(targets))) as pool:
                rows = list(pool.map(self._poll_one, targets))
        else:
            rows = []
        with self._lock:
            self._cached = (self._time(), rows)
        return rows


def runtime_provider(runtime) -> Callable:
    """Sections backed by a live driver Runtime via the state API."""

    def _connected_nodes() -> list:
        client = runtime.gcs_client  # snapshot: shutdown() may None it
        if client is None:
            return []
        from ray_tpu._private.rpc import RpcError

        try:
            return client.call("list_nodes")
        except (RpcError, OSError, AttributeError):
            return []

    collector = NodeStatsCollector(_connected_nodes)

    def provide(section: str):
        from ray_tpu.util import state

        if section == "cluster":
            return {
                "total_resources": runtime.cluster.total_resources(),
                "available_resources":
                    runtime.cluster.available_resources(),
                "alive_nodes": sum(
                    1 for n in runtime.gcs.list_nodes() if n.alive),
            }
        if section == "node_stats":
            return collector.collect()
        fn = {
            "nodes": state.list_nodes,
            "actors": state.list_actors,
            "tasks": state.list_tasks,
            "objects": state.list_objects,
            "placement_groups": state.list_placement_groups,
            "jobs": state.list_jobs,
        }.get(section)
        return fn(limit=1000) if fn else None

    return provide


def gcs_provider(gcs_server) -> Callable:
    """Sections backed by a head daemon's GcsServer."""

    collector = NodeStatsCollector(gcs_server._list_nodes)

    def provide(section: str):
        if section == "cluster":
            return {
                "total_resources": gcs_server._cluster_resources(),
                "alive_nodes": sum(
                    1 for n in gcs_server.gcs.list_nodes() if n.alive),
            }
        if section == "nodes":
            return gcs_server._list_nodes()
        if section == "node_stats":
            return collector.collect()
        if section == "jobs":
            return [dict(j, job_id=j.get("submission_id", ""))
                    for j in gcs_server.jobs.list() if j]
        if section in ("actors", "tasks", "objects",
                       "placement_groups"):
            return []  # driver-local tables; not mirrored to the head
        return None

    return provide
