"""User-facing metrics API (reference: python/ray/util/metrics.py —
Counter/Gauge/Histogram with tag_keys, exported via the node's metrics
agent to Prometheus).

Metrics register with the process-wide registry; the Prometheus agent
(ray_tpu._private.metrics_agent) serves them in text exposition format.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Sequence


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, "Metric"] = {}
        self._collectors: list = []

    def register(self, metric: "Metric") -> None:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                # Silent replacement would drop the old handle's series
                # from exposition while it keeps accumulating invisibly.
                raise ValueError(
                    f"Metric {metric.name!r} is already registered; "
                    f"reuse the existing instance")
            self._metrics[metric.name] = metric

    def add_collector(self, fn):
        """fn() -> list[str] of exposition lines, called per scrape.
        Returns a callable that deregisters the collector."""
        with self._lock:
            self._collectors.append(fn)

        def remove():
            with self._lock:
                try:
                    self._collectors.remove(fn)
                except ValueError:
                    pass

        return remove

    def scrape(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        lines: list[str] = []
        for metric in metrics:
            lines.extend(metric._expose())
        for fn in collectors:
            try:
                lines.extend(fn())
            except Exception:
                import logging

                logging.getLogger("ray_tpu").exception(
                    "metrics collector %r failed during scrape", fn)
        return "\n".join(lines) + "\n"

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()


REGISTRY = _Registry()


def _escape_label(value: str) -> str:
    """Prometheus text format: \\, ", and newline must be escaped in
    label values or the whole scrape fails to parse."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_tags(tags: dict[str, str] | None) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(tags.items()))
    return "{" + inner + "}"


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] | None = None):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._lock = threading.Lock()
        self._default_tags: dict[str, str] = {}
        REGISTRY.register(self)

    def set_default_tags(self, tags: dict[str, str]) -> None:
        with self._lock:
            self._default_tags = dict(tags)

    def _merge(self, tags: dict[str, str] | None) -> tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(
                f"Unknown tag(s) {sorted(extra)} for metric {self.name!r}; "
                f"declared tag_keys={list(self.tag_keys)}")
        return tuple(sorted(merged.items()))


class Counter(Metric):
    """Monotonic counter (reference: metrics.py Counter)."""

    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: dict[tuple, float] = defaultdict(float)

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        if value < 0:
            raise ValueError("Counter increments must be non-negative")
        key = self._merge(tags)
        with self._lock:
            self._values[key] += value

    def _expose(self) -> list[str]:
        with self._lock:
            items = list(self._values.items())
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} counter"]
        for key, value in items:
            lines.append(f"{self.name}{_fmt_tags(dict(key))} {value}")
        return lines


class Gauge(Metric):
    """Point-in-time value (reference: metrics.py Gauge)."""

    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, tags: dict | None = None) -> None:
        key = self._merge(tags)
        with self._lock:
            self._values[key] = float(value)

    def _expose(self) -> list[str]:
        with self._lock:
            items = list(self._values.items())
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} gauge"]
        for key, value in items:
            lines.append(f"{self.name}{_fmt_tags(dict(key))} {value}")
        return lines


class Histogram(Metric):
    """Bucketed distribution (reference: metrics.py Histogram)."""

    def __init__(self, name, description="", boundaries=None, tag_keys=None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                                  1.0, 2.5, 5.0, 10.0))
        self._buckets: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._counts: dict[tuple, int] = defaultdict(int)

    def observe(self, value: float, tags: dict | None = None) -> None:
        key = self._merge(tags)
        with self._lock:
            buckets = self._buckets.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            for i, bound in enumerate(self.boundaries):
                if value <= bound:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            self._sums[key] += value
            self._counts[key] += 1

    def _expose(self) -> list[str]:
        with self._lock:
            keys = list(self._buckets)
            snapshot = {k: (list(self._buckets[k]), self._sums[k],
                            self._counts[k]) for k in keys}
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} histogram"]
        for key, (buckets, total, count) in snapshot.items():
            tags = dict(key)
            cumulative = 0
            for bound, n in zip(self.boundaries, buckets):
                cumulative += n
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_tags({**tags, 'le': str(bound)})} {cumulative}")
            cumulative += buckets[-1]
            lines.append(
                f"{self.name}_bucket{_fmt_tags({**tags, 'le': '+Inf'})} "
                f"{cumulative}")
            lines.append(f"{self.name}_sum{_fmt_tags(tags)} {total}")
            lines.append(f"{self.name}_count{_fmt_tags(tags)} {count}")
        return lines
