"""joblib parallel backend over ray_tpu tasks.

Reference: python/ray/util/joblib/ (register_ray +
ray_backend.RayBackend): scikit-learn-style ``Parallel(...)`` fan-outs
run as framework tasks instead of local processes, so they ride the
cluster's scheduler, spillback, and object store.

Usage::

    import joblib
    from ray_tpu.util.joblib_backend import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        joblib.Parallel()(joblib.delayed(f)(x) for x in data)
"""

from __future__ import annotations

_run_joblib_batch = None  # created once, on first backend use


def register_ray_tpu() -> None:
    """Register the 'ray_tpu' joblib backend (idempotent)."""
    import joblib
    from joblib.parallel import ParallelBackendBase

    import ray_tpu

    class RayTpuBackend(ParallelBackendBase):
        """Each joblib batch becomes one task (reference:
        ray_backend.RayBackend submits batches as remote calls)."""

        supports_timeout = True
        uses_threads = False
        supports_sharedmem = False

        def configure(self, n_jobs=1, parallel=None, **kwargs):
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            total = ray_tpu.cluster_resources().get("CPU", 1)
            if n_jobs is None or n_jobs < 0:
                return max(1, int(total))
            return n_jobs

        def submit(self, func, callback=None):
            global _run_joblib_batch
            if _run_joblib_batch is None:
                @ray_tpu.remote
                def run_batch(batch):
                    return batch()

                _run_joblib_batch = run_batch
            ref = _run_joblib_batch.remote(func)
            return _RayTpuFuture(ref, callback)

        # joblib < 1.5 calls apply_async; >= 1.5 calls submit.
        apply_async = submit

        def abort_everything(self, ensure_ready=True):
            pass  # tasks already in flight run to completion

    class _RayTpuFuture:
        """joblib expects an AsyncResult-shaped handle."""

        def __init__(self, ref, callback):
            self._ref = ref
            if callback is not None:
                import threading

                def signal_done():
                    # Completion SIGNAL only (joblib retrieves the real
                    # value via get() below — fetching it here too
                    # would transfer every batch result twice). wait()
                    # also resolves for FAILED batches, so dispatch
                    # bookkeeping keeps advancing on errors.
                    try:
                        ray_tpu.wait([self._ref], num_returns=1)
                    except BaseException:  # noqa: BLE001
                        pass
                    try:
                        callback(None)
                    except BaseException:  # noqa: BLE001 — joblib
                        pass

                threading.Thread(target=signal_done,
                                 daemon=True).start()

        def get(self, timeout=None):
            from ray_tpu.exceptions import TaskError

            try:
                return ray_tpu.get(self._ref, timeout=timeout)
            except TaskError as exc:
                # joblib callers expect the USER's exception type (the
                # loky/threading backends re-raise it directly).
                raise exc.cause from exc

    joblib.register_parallel_backend("ray_tpu", RayTpuBackend)
