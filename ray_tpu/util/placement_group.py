"""Placement group user API.

Reference: python/ray/util/placement_group.py:146 (placement_group(...)),
plus the TPU pod-slice gang pattern from
python/ray/_private/accelerators/tpu.py:363-382.
"""

from __future__ import annotations

from typing import Any

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.object_ref import ObjectRef


class PlacementGroup:
    """Handle to a placement group."""

    def __init__(self, pg_id: PlacementGroupID, ready_ref: ObjectRef,
                 bundles: list[dict], strategy: str):
        self.id = pg_id
        self.ready_ref = ready_ref
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self) -> ObjectRef:
        """ObjectRef sealed once all bundles are committed."""
        return self.ready_ref

    def wait(self, timeout_seconds: float | None = None) -> bool:
        from ray_tpu.exceptions import GetTimeoutError

        runtime = worker_mod.auto_init()
        try:
            runtime.get([self.ready_ref], timeout=timeout_seconds)
            return True
        except GetTimeoutError:
            return False

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup,
                (self.id, self.ready_ref, self.bundle_specs, self.strategy))


def placement_group(bundles: list[dict], strategy: str = "PACK",
                    name: str = "", lifetime: str | None = None) -> PlacementGroup:
    runtime = worker_mod.auto_init()
    record = runtime.placement_groups.create(bundles, strategy, name=name)
    ready_ref = ObjectRef(record.ready_object_id)
    return PlacementGroup(record.pg_id, ready_ref, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    runtime = worker_mod.auto_init()
    runtime.placement_groups.remove(pg.id)


def placement_group_table() -> dict:
    runtime = worker_mod.auto_init()
    out = {}
    for record in runtime.placement_groups.list():
        out[record.pg_id.hex()] = {
            "placement_group_id": record.pg_id.hex(),
            "name": record.name,
            "strategy": record.strategy,
            "state": record.state,
            "bundles": {i: dict(b.resources) for i, b in enumerate(record.bundles)},
        }
    return out


def tpu_slice_bundle(num_chips: int, cpus_per_host: float = 8.0,
                     chips_per_host: int = 4) -> list[dict]:
    """Bundles reserving a whole TPU slice with STRICT_PACK semantics.

    TPU-native equivalent of claiming the TPU-{pod_type}-head gang
    resource (reference: tpu.py:382): one bundle per host, each holding
    that host's chips, so a slice is acquired all-or-nothing.
    """
    bundles = []
    remaining = num_chips
    while remaining > 0:
        chips = min(chips_per_host, remaining)
        bundles.append({"TPU": float(chips), "CPU": cpus_per_host})
        remaining -= chips
    return bundles
