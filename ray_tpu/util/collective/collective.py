"""Host-side collective API over the rendezvous store.

Reference: python/ray/util/collective/collective.py — the module-level
functions keep a per-process (here per-actor-thread) group table
(GroupManager :49) and every op goes through the group's backend. Ops
and signatures mirror collective.py:258-615.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Any

import numpy as np

import ray_tpu


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"




@dataclass
class _Group:
    name: str
    rank: int
    world_size: int
    store: Any
    seq: int = 0

    def next_key(self, op: str) -> str:
        self.seq += 1
        return f"{op}:{self.seq}"


class _GroupTable(threading.local):
    """Thread-local: each actor (its own thread) has its own ranks."""

    def __init__(self):
        self.groups: dict[str, _Group] = {}


_table = _GroupTable()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "store",
                          group_name: str = "default") -> None:
    """Join ``group_name`` as ``rank`` (reference: collective.py:120).

    Every participating actor/driver must call this; the named store
    actor is the rendezvous point (created once, get-if-exists).
    """
    if backend not in ("store", "gloo", "cpu"):
        raise ValueError(
            f"backend={backend!r}: host-side groups use the store backend"
            f" (device collectives live in ray_tpu.util.collective.xla)")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside [0, {world_size})")
    from ray_tpu.util.collective.store import CollectiveStore

    store = ray_tpu.remote(CollectiveStore).options(
        name=f"collective::{group_name}", get_if_exists=True,
        max_concurrency=max(64, world_size * 4)).remote(world_size)
    actual = ray_tpu.get(store.world_size.remote())
    if actual != world_size:
        raise ValueError(
            f"group {group_name!r} exists with world_size={actual}, "
            f"asked for {world_size}")
    _table.groups[group_name] = _Group(
        name=group_name, rank=rank, world_size=world_size, store=store)


def destroy_collective_group(group_name: str = "default") -> None:
    group = _table.groups.pop(group_name, None)
    if group is not None and group.rank == 0:
        try:
            ray_tpu.kill(group.store)
        except Exception:  # noqa: BLE001 — another rank already killed it
            pass


def _group(group_name: str) -> _Group:
    try:
        return _table.groups[group_name]
    except KeyError:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"actor — call init_collective_group() first") from None


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_world_size(group_name: str = "default") -> int:
    return _group(group_name).world_size


# ------------------------------------------------------------------ ops


def _exchange(group: _Group, op: str, payload) -> dict[int, Any]:
    key = group.next_key(op)
    return ray_tpu.get(
        group.store.exchange.remote(key, group.rank, payload),
        timeout=120.0)


def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    """Reference: collective.py:258. Returns the reduced array.

    The store reduces incrementally as contributions arrive, so each
    rank ships one array and receives one array — O(world) traffic
    (the round-1 fan-out of the full contribution set was O(world^2)).
    """
    group = _group(group_name)
    key = group.next_key("allreduce")
    return ray_tpu.get(
        group.store.reduce_exchange.remote(
            key, group.rank, np.asarray(tensor), op.value),
        timeout=120.0)


def barrier(group_name: str = "default") -> None:
    """Reference: collective.py:298."""
    _exchange(_group(group_name), "barrier", None)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Reference: collective.py:373. Returns src's tensor on every rank.

    Only the source ships a payload; receivers block for the value
    (no receiver-receiver barrier, matching NCCL broadcast).
    """
    group = _group(group_name)
    if not 0 <= src_rank < group.world_size:
        raise ValueError(
            f"broadcast: src_rank {src_rank} outside "
            f"[0, {group.world_size}) — no rank would ever send")
    key = group.next_key("broadcast")
    payload = np.asarray(tensor) if group.rank == src_rank else None
    return ray_tpu.get(
        group.store.broadcast_value.remote(
            key, group.rank, payload, src_rank),
        timeout=120.0)


def allgather(tensor, group_name: str = "default") -> list:
    """Reference: collective.py:423. Returns [rank0_tensor, ...]."""
    group = _group(group_name)
    contributions = _exchange(group, "allgather", np.asarray(tensor))
    return [contributions[r] for r in range(group.world_size)]


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    """Reference: collective.py:472. Each rank gets its 1/world_size
    chunk (along axis 0) of the reduction."""
    group = _group(group_name)
    arr = np.asarray(tensor)
    if arr.shape[0] % group.world_size:
        raise ValueError(
            f"reducescatter: leading dim {arr.shape[0]} not divisible by "
            f"world_size {group.world_size}")
    key = group.next_key("reducescatter")
    # Store-side reduce; each rank receives only its shard.
    return ray_tpu.get(
        group.store.reduce_scatter.remote(
            key, group.rank, arr, op.value),
        timeout=120.0)


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    """Reference: collective.py:531."""
    group = _group(group_name)
    ray_tpu.get(group.store.p2p_put.remote(
        (group.rank, dst_rank, tag), np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    """Reference: collective.py:594. Blocks for a matching send."""
    group = _group(group_name)
    return ray_tpu.get(group.store.p2p_take.remote(
        (src_rank, group.rank, tag)), timeout=120.0)
