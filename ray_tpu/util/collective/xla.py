"""Device-plane collectives: XLA over ICI via shard_map.

The reference's tensor plane is NCCL
(util/collective/collective_group/nccl_collective_group.py); on TPU the
equivalent plane is XLA collectives over the chip interconnect (ICI),
expressed as `jax.lax` ops inside `shard_map` over a
`jax.sharding.Mesh`. Two layers here:

1. In-SPMD primitives — use directly inside your own shard_map'd
   function: ``psum``, ``pmean``, ``all_gather``, ``ppermute``,
   ``all_to_all``, ``axis_index`` (re-exported from jax.lax so user
   code imports one namespace).
2. Host-level helpers — take a host array whose LEADING axis enumerates
   per-device shards (the moral equivalent of "each worker holds a
   tensor"), run ONE compiled collective over the mesh, return the
   result. These are what actor code calls when it wants a one-shot
   device-backed collective without writing shard_map by hand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map

# In-SPMD primitives (layer 1).
psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
all_gather = lax.all_gather
ppermute = lax.ppermute
all_to_all = lax.all_to_all
axis_index = lax.axis_index


def default_mesh(num_devices: int | None = None,
                 axis_name: str = "x") -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    return Mesh(np.asarray(devices[:n]), (axis_name,))


def _sharded(x, mesh: Mesh, axis_name: str):
    x = jnp.asarray(x)
    n = mesh.shape[axis_name]
    if x.shape[0] != n:
        raise ValueError(
            f"leading axis {x.shape[0]} must equal mesh axis "
            f"{axis_name}={n} (one shard per device)")
    return jax.device_put(
        x, NamedSharding(mesh, P(axis_name, *([None] * (x.ndim - 1)))))


def device_allreduce(x, mesh: Mesh | None = None, axis_name: str = "x"):
    """x: [n_devices, ...] (shard i lives on device i) → sum over shards,
    reduced on-device (psum over ICI), replicated result returned."""
    mesh = mesh or default_mesh(axis_name=axis_name)

    @jax.jit
    def fn(x):
        return shard_map(
            lambda s: psum(s, axis_name), mesh=mesh,
            in_specs=P(axis_name), out_specs=P())(x)

    return np.asarray(fn(_sharded(x, mesh, axis_name)))[0]


def device_allgather(x, mesh: Mesh | None = None, axis_name: str = "x"):
    """x: [n_devices, ...] → [n_devices, ...] gathered on every device."""
    mesh = mesh or default_mesh(axis_name=axis_name)

    @jax.jit
    def fn(x):
        # all_gather's replication isn't statically inferred → check_vma
        # off for this one wrapper.
        return shard_map(
            lambda s: all_gather(s, axis_name, axis=0, tiled=True),
            mesh=mesh, in_specs=P(axis_name), out_specs=P(),
            check_vma=False)(x)

    return np.asarray(fn(_sharded(x, mesh, axis_name)))


def device_reducescatter(x, mesh: Mesh | None = None,
                         axis_name: str = "x"):
    """x: [n_devices, m, ...] → each device ends with its [m/n] chunk of
    the sum; returned as [n_devices, m/n, ...] (chunk i from device i)."""
    mesh = mesh or default_mesh(axis_name=axis_name)

    @jax.jit
    def fn(x):
        return shard_map(
            lambda s: lax.psum_scatter(
                s[0], axis_name, scatter_dimension=0, tiled=True)[None],
            mesh=mesh, in_specs=P(axis_name), out_specs=P(axis_name))(x)

    return np.asarray(fn(_sharded(x, mesh, axis_name)))


def device_ring_shift(x, mesh: Mesh | None = None, axis_name: str = "x",
                      shift: int = 1):
    """Ring ppermute: shard i moves to device (i+shift) % n — the
    building block of ring attention / pipeline comm."""
    mesh = mesh or default_mesh(axis_name=axis_name)
    n = mesh.shape[axis_name]
    perm = [(i, (i + shift) % n) for i in range(n)]

    @jax.jit
    def fn(x):
        return shard_map(
            lambda s: ppermute(s, axis_name, perm), mesh=mesh,
            in_specs=P(axis_name), out_specs=P(axis_name))(x)

    return np.asarray(fn(_sharded(x, mesh, axis_name)))
