"""Explicit collective communication between actors.

Reference: python/ray/util/collective/collective.py (:120
init_collective_group, :258 allreduce, :298 barrier, :373 broadcast,
:423 allgather, :472 reducescatter, :531/:594 send/recv) with NCCL/Gloo
backends (collective_group/nccl_collective_group.py, 821 LoC).

TPU-native split (SURVEY §7 step 4):
- ``backend="store"`` — the Gloo-equivalent host-side backend: a named
  rendezvous actor carries contributions over the object store. Used by
  CPU rollout actors and control-plane gangs.
- ``ray_tpu.util.collective.xla`` — the NCCL-equivalent device plane:
  XLA collectives (psum/all_gather/ppermute/...) over a
  jax.sharding.Mesh via shard_map, riding ICI. Use inside SPMD
  programs; the host API here is for actor-to-actor exchange.
"""

from ray_tpu.util.collective.collective import (
    ReduceOp,
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    get_rank,
    get_world_size,
    init_collective_group,
    recv,
    reducescatter,
    send,
)
from ray_tpu.util.collective import xla

__all__ = [
    "ReduceOp", "allgather", "allreduce", "barrier", "broadcast",
    "destroy_collective_group", "get_rank", "get_world_size",
    "init_collective_group", "recv", "reducescatter", "send", "xla",
]
