"""Rendezvous actor backing the host-side collective backend.

Reference: the NCCL group's bootstrap in
python/ray/util/collective/collective_group/nccl_collective_group.py
rendezvouses through a named store actor (Rendezvous/NCCLUniqueIDStore);
here the store carries the *data itself* (Gloo-equivalent CPU plane):
every rank contributes a payload for (op sequence number), the store
releases the full set once world_size contributions arrived.
"""

from __future__ import annotations

import threading
import time
from typing import Any


class CollectiveStore:
    """Runs as a named actor, one per collective group."""

    def __init__(self, world_size: int):
        self._world = world_size
        self._lock = threading.Condition()
        # op_key -> {rank: payload}
        self._pending: dict[str, dict[int, Any]] = {}
        # op_key -> number of ranks that already collected (for cleanup)
        self._collected: dict[str, int] = {}
        # (src, dst, tag) point-to-point mailboxes — FIFO queues, so
        # back-to-back sends before the first recv are not lost.
        self._mailbox: dict[tuple, list] = {}

    def world_size(self) -> int:
        return self._world

    def exchange(self, op_key: str, rank: int, payload: Any,
                 timeout_s: float = 60.0) -> dict[int, Any]:
        """Contribute and block until every rank contributed; returns
        {rank: payload} for the whole group."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            slot = self._pending.setdefault(op_key, {})
            if rank in slot:
                raise RuntimeError(
                    f"rank {rank} contributed twice to {op_key} — "
                    f"collective calls out of order?")
            slot[rank] = payload
            self._lock.notify_all()
            while len(slot) < self._world:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"collective {op_key}: only {len(slot)}/"
                        f"{self._world} ranks arrived within {timeout_s}s")
                self._lock.wait(remaining)
            result = dict(slot)
            self._collected[op_key] = self._collected.get(op_key, 0) + 1
            if self._collected[op_key] >= self._world:
                del self._pending[op_key]
                del self._collected[op_key]
            return result

    # -------------------------------------------------- reducing exchanges

    def reduce_exchange(self, op_key: str, rank: int, payload,
                        reduce_op: str, timeout_s: float = 60.0):
        """Allreduce with STORE-SIDE incremental reduction.

        Each rank ships its array once and receives ONE reduced array —
        O(world) traffic and O(1) store memory per op, vs exchange()'s
        O(world^2) full-set fan-out (round-1 review finding). MEAN is
        SUM here; the caller divides.
        """
        import numpy as np

        deadline = time.monotonic() + timeout_s
        with self._lock:
            slot = self._pending.setdefault(
                op_key, {"acc": None, "count": 0, "ranks": set()})
            if rank in slot["ranks"]:
                raise RuntimeError(
                    f"rank {rank} contributed twice to {op_key} — "
                    f"collective calls out of order?")
            slot["ranks"].add(rank)
            arr = np.asarray(payload)
            if slot["acc"] is None:
                slot["acc"] = arr.copy()
            else:
                # Deterministic dtype promotion regardless of arrival
                # order (the in-place op alone would pin the dtype to
                # whichever rank arrived first).
                common = np.result_type(slot["acc"].dtype, arr.dtype)
                if slot["acc"].dtype != common:
                    slot["acc"] = slot["acc"].astype(common)
                if reduce_op in ("sum", "mean"):
                    slot["acc"] = slot["acc"] + arr
                elif reduce_op == "product":
                    slot["acc"] = slot["acc"] * arr
                elif reduce_op == "min":
                    slot["acc"] = np.minimum(slot["acc"], arr)
                elif reduce_op == "max":
                    slot["acc"] = np.maximum(slot["acc"], arr)
                else:
                    raise ValueError(
                        f"unknown reduce op {reduce_op!r}")
            slot["count"] += 1
            self._lock.notify_all()
            while slot["count"] < self._world:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # Drop the half-filled slot: the op is broken for
                    # the whole group anyway (peers time out too) and
                    # the entry must not leak.
                    self._pending.pop(op_key, None)
                    self._collected.pop(op_key, None)
                    raise TimeoutError(
                        f"collective {op_key}: only {slot['count']}/"
                        f"{self._world} ranks arrived within {timeout_s}s")
                self._lock.wait(remaining)
            # Fresh copy per rank: in-process actors share the object
            # store zero-copy, so returning the live accumulator would
            # alias one mutable buffer across every rank.
            result = slot["acc"].copy()
            self._collected[op_key] = self._collected.get(op_key, 0) + 1
            if self._collected[op_key] >= self._world:
                self._pending.pop(op_key, None)
                del self._collected[op_key]
            return result

    def reduce_scatter(self, op_key: str, rank: int, payload,
                       reduce_op: str, timeout_s: float = 60.0):
        """Store-side reduce, then each rank takes only its shard."""
        import numpy as np

        reduced = self.reduce_exchange(op_key, rank, payload, reduce_op,
                                       timeout_s)
        shards = np.array_split(reduced, self._world, axis=0)
        return shards[rank]

    def broadcast_value(self, op_key: str, rank: int, payload,
                        src_rank: int, timeout_s: float = 60.0):
        """Only the source ships a payload; receivers block for it.

        No full-group barrier (matches NCCL broadcast: receivers do not
        synchronize with each other).
        """
        deadline = time.monotonic() + timeout_s
        with self._lock:
            slot = self._pending.setdefault(
                op_key, {"value": None, "have": False, "taken": 0})
            if rank == src_rank:
                slot["value"] = payload
                slot["have"] = True
                self._lock.notify_all()
            while not slot["have"]:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._pending.pop(op_key, None)
                    raise TimeoutError(
                        f"broadcast {op_key}: src rank {src_rank} "
                        f"never arrived within {timeout_s}s")
                self._lock.wait(remaining)
            value = slot["value"]
            slot["taken"] += 1
            if slot["taken"] >= self._world:
                self._pending.pop(op_key, None)
            # Copy per rank (in-process zero-copy aliasing; the src
            # mutating its weights later must not change receivers').
            import numpy as np

            return np.asarray(value).copy() if value is not None else None

    # ------------------------------------------------------ point-to-point

    def p2p_put(self, key: tuple, payload: Any) -> None:
        with self._lock:
            self._mailbox.setdefault(key, []).append(payload)
            self._lock.notify_all()

    def p2p_take(self, key: tuple, timeout_s: float = 60.0) -> Any:
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while not self._mailbox.get(key):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"recv {key}: no matching send "
                                       f"within {timeout_s}s")
                self._lock.wait(remaining)
            queue = self._mailbox[key]
            payload = queue.pop(0)
            if not queue:
                del self._mailbox[key]
            return payload
