"""Rendezvous actor backing the host-side collective backend.

Reference: the NCCL group's bootstrap in
python/ray/util/collective/collective_group/nccl_collective_group.py
rendezvouses through a named store actor (Rendezvous/NCCLUniqueIDStore);
here the store carries the *data itself* (Gloo-equivalent CPU plane):
every rank contributes a payload for (op sequence number), the store
releases the full set once world_size contributions arrived.
"""

from __future__ import annotations

import threading
import time
from typing import Any


class CollectiveStore:
    """Runs as a named actor, one per collective group."""

    def __init__(self, world_size: int):
        self._world = world_size
        self._lock = threading.Condition()
        # op_key -> {rank: payload}
        self._pending: dict[str, dict[int, Any]] = {}
        # op_key -> number of ranks that already collected (for cleanup)
        self._collected: dict[str, int] = {}
        # (src, dst, tag) point-to-point mailboxes — FIFO queues, so
        # back-to-back sends before the first recv are not lost.
        self._mailbox: dict[tuple, list] = {}

    def world_size(self) -> int:
        return self._world

    def exchange(self, op_key: str, rank: int, payload: Any,
                 timeout_s: float = 60.0) -> dict[int, Any]:
        """Contribute and block until every rank contributed; returns
        {rank: payload} for the whole group."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            slot = self._pending.setdefault(op_key, {})
            if rank in slot:
                raise RuntimeError(
                    f"rank {rank} contributed twice to {op_key} — "
                    f"collective calls out of order?")
            slot[rank] = payload
            self._lock.notify_all()
            while len(slot) < self._world:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"collective {op_key}: only {len(slot)}/"
                        f"{self._world} ranks arrived within {timeout_s}s")
                self._lock.wait(remaining)
            result = dict(slot)
            self._collected[op_key] = self._collected.get(op_key, 0) + 1
            if self._collected[op_key] >= self._world:
                del self._pending[op_key]
                del self._collected[op_key]
            return result

    # ------------------------------------------------------ point-to-point

    def p2p_put(self, key: tuple, payload: Any) -> None:
        with self._lock:
            self._mailbox.setdefault(key, []).append(payload)
            self._lock.notify_all()

    def p2p_take(self, key: tuple, timeout_s: float = 60.0) -> Any:
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while not self._mailbox.get(key):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"recv {key}: no matching send "
                                       f"within {timeout_s}s")
                self._lock.wait(remaining)
            queue = self._mailbox[key]
            payload = queue.pop(0)
            if not queue:
                del self._mailbox[key]
            return payload
