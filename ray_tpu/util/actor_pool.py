"""ActorPool — load-balance tasks over a fixed set of actors.

Reference: python/ray/util/actor_pool.py (same API surface: map,
map_unordered, submit/get_next/get_next_unordered, has_next,
push/pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

import ray_tpu


class _TaskError:
    """Buffered failure: re-raised when its slot is consumed."""

    def __init__(self, exc: Exception):
        self.exc = exc


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle = list(actors)
        self._future_to_actor: dict = {}          # future -> (idx, actor)
        self._index_to_future: dict[int, Any] = {}
        self._returned: dict[int, Any] = {}       # completed, unconsumed
        self._consumed: set[int] = set()          # taken unordered
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list[tuple[Callable, Any]] = []

    # -- bulk API -----------------------------------------------------
    def map(self, fn: Callable, values: Iterable) -> Iterator:
        """Ordered results; ``fn(actor, value) -> ObjectRef``."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterator:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- incremental API ----------------------------------------------
    def submit(self, fn: Callable, value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            # No idle actor: queue; dispatched when one frees up.
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor or self._pending_submits
                    or self._returned)

    def _return_actor(self, actor: Any) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def _fetch_one(self, timeout: float | None) -> int:
        """Wait for any in-flight future; buffer its value. -> index."""
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("ActorPool result wait timed out")
        future = ready[0]
        index, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(index, None)
        self._return_actor(actor)
        # A failed task must still populate _returned, otherwise
        # get_next() re-enters _fetch_one with no future left for this
        # index and hangs; store the error and raise it at consumption.
        try:
            self._returned[index] = ray_tpu.get(future)
        except Exception as exc:  # noqa: BLE001 — surfaced in get_next*
            self._returned[index] = _TaskError(exc)
        return index

    def _skip_consumed(self) -> None:
        while self._next_return_index in self._consumed:
            self._consumed.discard(self._next_return_index)
            self._next_return_index += 1

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in SUBMISSION order (skipping results already
        taken via get_next_unordered)."""
        self._skip_consumed()
        if not self.has_next():
            raise StopIteration("no more results")
        index = self._next_return_index
        while index not in self._returned:
            self._fetch_one(timeout)
        self._next_return_index += 1
        self._skip_consumed()
        value = self._returned.pop(index)
        if isinstance(value, _TaskError):
            raise value.exc
        return value

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Next result in COMPLETION order."""
        if not self.has_next():
            raise StopIteration("no more results")
        if not self._returned:
            self._fetch_one(timeout)
        index = min(self._returned)
        self._consumed.add(index)
        value = self._returned.pop(index)
        if isinstance(value, _TaskError):
            raise value.exc
        return value

    # -- membership ---------------------------------------------------
    def push(self, actor: Any) -> None:
        """Add an (idle) actor to the pool."""
        self._return_actor(actor)

    def pop_idle(self) -> Any | None:
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle)
