"""multiprocessing.Pool shim over the task runtime.

Reference: python/ray/util/multiprocessing/ (Pool running on actors so
existing multiprocessing code ports by changing an import). Methods:
apply/apply_async, map/map_async, imap/imap_unordered, starmap —
including ``chunksize`` and the ``processes`` concurrency bound, and
stdlib ``multiprocessing.TimeoutError`` on timeouts, so except clauses
in ported code keep working.
"""

from __future__ import annotations

from multiprocessing import TimeoutError as MpTimeoutError
from typing import Any, Callable, Iterable

import ray_tpu


def _chunks(iterable: Iterable, chunksize: int) -> list[list]:
    items = list(iterable)
    chunksize = max(1, chunksize)
    return [items[i:i + chunksize]
            for i in range(0, len(items), chunksize)]


class AsyncResult:
    """Reference: multiprocessing.pool.AsyncResult protocol.

    ``refs`` are chunk tasks; ``get`` flattens chunk outputs back to
    per-item results.
    """

    def __init__(self, refs, single: bool, chunked: bool = False):
        self._refs = refs
        self._single = single
        self._chunked = chunked

    def get(self, timeout: float | None = None):
        try:
            values = ray_tpu.get(self._refs, timeout=timeout)
        except Exception as exc:  # noqa: BLE001 — translate timeouts
            if isinstance(exc, TimeoutError):
                raise MpTimeoutError(str(exc)) from exc
            raise
        if self._chunked:
            values = [v for chunk in values for v in chunk]
        return values[0] if self._single else values

    def wait(self, timeout: float | None = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs,
                                num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """Task-backed pool. ``processes`` bounds in-flight task chunks
    (Pool(1) serializes work like the stdlib); with
    ``init(process_workers=N)`` chunks run on real OS processes."""

    def __init__(self, processes: int | None = None,
                 initializer: Callable | None = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = max(1, processes or 4)
        self._closed = False
        # The initializer contract is per-worker-process; our tasks
        # share pool workers, so run it lazily inside each task chunk.
        self._initializer = initializer
        self._initargs = initargs

    def _chunk_fn(self, func: Callable, star: bool = False) -> Callable:
        init, initargs = self._initializer, self._initargs

        def run_chunk(items: list) -> list:
            if init is not None:
                init(*initargs)
            if star:
                return [func(*args) for args in items]
            return [func(x) for x in items]

        return run_chunk

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool is closed")

    def _submit_bounded(self, remote_fn, chunks: list) -> list:
        """Submit respecting the `processes` in-flight bound; returns
        refs in submission order."""
        refs: list = []
        in_flight: list = []
        for chunk in chunks:
            while len(in_flight) >= self._processes:
                _, in_flight = ray_tpu.wait(in_flight, num_returns=1)
            ref = remote_fn.remote(chunk)
            refs.append(ref)
            in_flight.append(ref)
        return refs

    # -- apply --------------------------------------------------------
    def apply(self, func: Callable, args: tuple = (),
              kwds: dict | None = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args: tuple = (),
                    kwds: dict | None = None) -> AsyncResult:
        self._check_open()
        init, initargs = self._initializer, self._initargs

        def call():
            if init is not None:
                init(*initargs)
            return func(*args, **(kwds or {}))

        return AsyncResult([ray_tpu.remote(call).remote()], single=True)

    # -- map ----------------------------------------------------------
    def map(self, func: Callable, iterable: Iterable,
            chunksize: int = 1) -> list:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func: Callable, iterable: Iterable,
                  chunksize: int = 1) -> AsyncResult:
        self._check_open()
        remote_fn = ray_tpu.remote(self._chunk_fn(func))
        refs = self._submit_bounded(remote_fn,
                                    _chunks(iterable, chunksize))
        return AsyncResult(refs, single=False, chunked=True)

    def starmap(self, func: Callable, iterable: Iterable,
                chunksize: int = 1) -> list:
        self._check_open()
        remote_fn = ray_tpu.remote(self._chunk_fn(func, star=True))
        refs = self._submit_bounded(remote_fn,
                                    _chunks(iterable, chunksize))
        return [v for chunk in ray_tpu.get(refs) for v in chunk]

    def imap(self, func: Callable, iterable: Iterable,
             chunksize: int = 1):
        self._check_open()
        remote_fn = ray_tpu.remote(self._chunk_fn(func))
        chunks = _chunks(iterable, chunksize)
        in_flight: list = []
        pending = list(chunks)
        submitted: list = []
        # Keep `processes` chunks in flight; yield in submission order.
        while pending or submitted:
            while pending and len(in_flight) < self._processes:
                ref = remote_fn.remote(pending.pop(0))
                submitted.append(ref)
                in_flight.append(ref)
            ref = submitted.pop(0)
            for value in ray_tpu.get(ref):
                yield value
            in_flight = [r for r in in_flight if r is not ref]

    def imap_unordered(self, func: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check_open()
        remote_fn = ray_tpu.remote(self._chunk_fn(func))
        pending_chunks = _chunks(iterable, chunksize)
        in_flight: list = []
        while pending_chunks or in_flight:
            while pending_chunks and len(in_flight) < self._processes:
                in_flight.append(
                    remote_fn.remote(pending_chunks.pop(0)))
            ready, in_flight = ray_tpu.wait(in_flight, num_returns=1)
            for value in ray_tpu.get(ready[0]):
                yield value

    # -- lifecycle ----------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still open")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
