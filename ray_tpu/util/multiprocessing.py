"""multiprocessing.Pool shim over the task runtime.

Reference: python/ray/util/multiprocessing/ (Pool running on actors so
existing multiprocessing code ports by changing an import). Methods:
apply/apply_async, map/map_async, imap/imap_unordered, starmap.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import ray_tpu


class AsyncResult:
    """Reference: multiprocessing.pool.AsyncResult protocol."""

    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: float | None = None):
        values = ray_tpu.get(self._refs, timeout=timeout)
        return values[0] if self._single else values

    def wait(self, timeout: float | None = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs,
                                num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """Task-backed process pool (each call is a ray_tpu task, so with
    ``init(process_workers=N)`` work runs on real OS processes)."""

    def __init__(self, processes: int | None = None,
                 initializer: Callable | None = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or 4
        self._closed = False
        # The initializer contract is per-worker-process; our tasks
        # share pool workers, so run it lazily inside each task chunk.
        self._initializer = initializer
        self._initargs = initargs

    def _wrap(self, func: Callable) -> Callable:
        init, initargs = self._initializer, self._initargs
        if init is None:
            return func

        def wrapped(*a, **kw):
            init(*initargs)
            return func(*a, **kw)

        return wrapped

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool is closed")

    # -- apply --------------------------------------------------------
    def apply(self, func: Callable, args: tuple = (),
              kwds: dict | None = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args: tuple = (),
                    kwds: dict | None = None) -> AsyncResult:
        self._check_open()
        remote_fn = ray_tpu.remote(self._wrap(func))
        return AsyncResult([remote_fn.remote(*args, **(kwds or {}))],
                           single=True)

    # -- map ----------------------------------------------------------
    def map(self, func: Callable, iterable: Iterable) -> list:
        return self.map_async(func, iterable).get()

    def map_async(self, func: Callable, iterable: Iterable) -> AsyncResult:
        self._check_open()
        remote_fn = ray_tpu.remote(self._wrap(func))
        return AsyncResult([remote_fn.remote(x) for x in iterable],
                           single=False)

    def starmap(self, func: Callable, iterable: Iterable) -> list:
        self._check_open()
        remote_fn = ray_tpu.remote(self._wrap(func))
        return ray_tpu.get(
            [remote_fn.remote(*args) for args in iterable])

    def imap(self, func: Callable, iterable: Iterable):
        self._check_open()
        remote_fn = ray_tpu.remote(self._wrap(func))
        refs = [remote_fn.remote(x) for x in iterable]
        for ref in refs:  # submission order
            yield ray_tpu.get(ref)

    def imap_unordered(self, func: Callable, iterable: Iterable):
        self._check_open()
        remote_fn = ray_tpu.remote(self._wrap(func))
        pending = [remote_fn.remote(x) for x in iterable]
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield ray_tpu.get(ready[0])

    # -- lifecycle ----------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still open")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
