"""ray_tpu.util — user-facing utilities.

Reference: python/ray/util/ (ActorPool, Queue, collective,
placement_group helpers, scheduling strategies, metrics, state API).
"""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.metrics import Counter, Gauge, Histogram
from ray_tpu.util.queue import Empty, Full, Queue

__all__ = [
    "ActorPool",
    "Counter",
    "Empty",
    "Full",
    "Gauge",
    "Histogram",
    "Queue",
]
