"""ray_tpu.util.client — remote drivers over RPC (Ray Client).

Reference: python/ray/util/client/ (server/server.py:RayletServicer +
client worker: a thin proxy where remote()/get()/put() run against a
cluster-hosted runtime instead of a local one).

Usage::

    from ray_tpu.util import client

    api = client.connect("HEAD_HOST:CLIENT_PORT")
    square = api.remote(lambda x: x * x)      # or a def
    assert api.get(square.remote(7)) == 49
    api.disconnect()

The head daemon hosts the server (``python -m ray_tpu start --head``
advertises ``client_address`` in the session dir); any machine that can
reach it runs tasks/actors ON the cluster runtime with no local
ray_tpu.init().
"""

from ray_tpu.util.client.api import (
    ClientAPI,
    ClientActorHandle,
    ClientObjectRef,
    connect,
)
from ray_tpu.util.client.server import ClientServer

__all__ = [
    "ClientAPI",
    "ClientActorHandle",
    "ClientObjectRef",
    "ClientServer",
    "connect",
]
