"""Client server — hosts remote drivers against the local runtime.

Reference: python/ray/util/client/server/server.py (RayletServicer:
gRPC endpoints Schedule/GetObject/PutObject/WaitObject/Terminate
backed by the server-side ray worker). Here the endpoints ride the
framework RPC layer (rpc.py) and execute against this process's
Runtime (the head's, when embedded in the head daemon).

Object lifetime: every ref returned to a client is pinned in
``self._refs`` until the client disconnects or releases it, so the
runtime cannot GC results the client still names.
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu._private import serialization
from ray_tpu._private.rpc import RpcServer


class ClientServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._server = RpcServer(host, port)
        self._refs: dict[str, Any] = {}       # ref hex -> ObjectRef
        # Borrower protocol (reference: reference_count.h:61 — the owner
        # tracks which processes borrow each object and defers the free
        # until every borrower releases): ref hex -> {borrower ids}.
        # Workers that deserialize a driver-owned ref register here; a
        # pin with live borrowers survives the driver dropping its own
        # handles AND other borrowers' releases.
        self._borrowers: dict[str, set] = {}
        # (key, borrower_id) -> last keepalive; borrow claims are
        # leases so a crashed borrower cannot pin objects forever.
        self._borrow_seen: dict[tuple, float] = {}
        import os as _os

        self._borrow_ttl_s = float(
            _os.environ.get("RAY_TPU_BORROW_TTL_S", "60"))
        self._stop = threading.Event()
        self._janitor: threading.Thread | None = None
        # Explicitly released keys: _resolve must reject them even while
        # the (deferred) refcount reaper hasn't evicted the object yet.
        self._released: set[str] = set()
        self._actors: dict[str, Any] = {}     # actor hex -> ActorHandle
        self._lock = threading.Lock()
        s = self._server
        s.register("ping", lambda: "pong")
        s.register("client_put", self.put)
        # Long-polls dispatch off the connection loop: a pipelined
        # proxy (worker_client's MuxRpcClient) interleaves borrow
        # flushes and releases with a blocking get on one socket.
        s.register("client_get", self.get, concurrent=True)
        s.register("client_wait", self.wait, concurrent=True)
        s.register("client_task", self.task)
        s.register("client_create_actor", self.create_actor)
        s.register("client_actor_call", self.actor_call)
        s.register("client_kill_actor", self.kill_actor)
        s.register("client_release", self.release)
        s.register("client_borrow", self.borrow)
        s.register("client_disconnect", self.disconnect_cleanup)
        s.register("client_cancel", self.cancel)
        s.register("client_unblock", self.unblock)
        s.register("client_get_actor", self.get_actor)
        s.register("client_cluster_resources", self.cluster_resources)

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def address(self) -> str:
        return self._server.address

    def start(self) -> "ClientServer":
        self._server.start()
        self._janitor = threading.Thread(
            target=self._janitor_loop, daemon=True,
            name="ray_tpu-client-borrow-janitor")
        self._janitor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.stop()

    # -- helpers ------------------------------------------------------
    def _track(self, ref, claimant: str | None = None) -> str:
        """Pin a handed-out ref, claimed by the RECEIVING client's
        identity: the pin survives until every claimant/borrower
        releases, so one party dropping a shared ref cannot free it
        under another still holding it."""
        key = ref.id().hex()
        with self._lock:
            self._refs[key] = ref
            self._released.discard(key)
            self._borrowers.setdefault(key, set()).add(
                claimant or "__direct__")
        return key

    def _resolve(self, key: str):
        with self._lock:
            if key in self._released:
                raise KeyError(f"released client ref {key}")
            ref = self._refs.get(key)
        if ref is not None:
            return ref
        # Not server-tracked: possibly a driver-created ref that reached
        # the client (passed into a pool task's args and echoed back by
        # nested code). This process is the owner, so a bare id the
        # local store knows resolves directly; anything else is a
        # released/bogus key and must fail, not block forever.
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_ref import ObjectRef
        from ray_tpu._private.worker import global_runtime

        runtime = global_runtime()
        oid = ObjectID(bytes.fromhex(key))
        if runtime is not None and (runtime.store.contains(oid)
                                    or runtime.store.is_pending(oid)):
            return ObjectRef(oid)
        raise KeyError(f"unknown/released client ref {key}")

    def _resolve_actor(self, key: str):
        with self._lock:
            handle = self._actors.get(key)
        if handle is not None:
            return handle
        # Driver-created ActorHandle passed into a pool task: rebuild
        # from the id against the local runtime's actor table.
        from ray_tpu._private.ids import ActorID
        from ray_tpu._private.worker import global_runtime
        from ray_tpu.actor import ActorHandle

        actor_id = ActorID(bytes.fromhex(key))
        runtime = global_runtime()
        record = (runtime.gcs.get_actor(actor_id)
                  if runtime is not None else None)
        if record is None:
            raise KeyError(f"unknown client actor {key}")
        return ActorHandle(actor_id, record.class_name)

    def _deserialize_args(self, args_blob: bytes):
        args, kwargs = serialization.deserialize_from_buffer(
            memoryview(args_blob))

        def convert(v):
            # Symmetric with ClientAPI._marshal: placeholders may sit
            # inside lists/tuples/dicts, not just at the top level.
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "__ref__":
                return self._resolve(v[1])
            if isinstance(v, tuple) and len(v) == 2 \
                    and v[0] == "__actor__":
                return self._resolve_actor(v[1])
            # EXACT container types only: tuple/dict subclasses
            # (namedtuples, OrderedDicts) pass through untouched —
            # rebuilding them as plain containers would mangle them.
            if type(v) is list:
                return [convert(x) for x in v]
            if type(v) is tuple:
                return tuple(convert(x) for x in v)
            if type(v) is dict:
                return {k: convert(x) for k, x in v.items()}
            return v

        return (tuple(convert(a) for a in args),
                {k: convert(v) for k, v in kwargs.items()})

    # -- endpoints ----------------------------------------------------
    def put(self, value_blob: bytes, claimant: str | None = None) -> str:
        import ray_tpu

        value = serialization.deserialize_from_buffer(
            memoryview(value_blob))
        return self._track(ray_tpu.put(value), claimant)

    @staticmethod
    def _block_ctx(block_token: str | None):
        """The caller is a pool worker blocked inside one of OUR tasks:
        release that task's CPU admission while it waits (cross-process
        BlockedResourceContext — reference: workers blocked in ray.get
        return their CPU to the raylet)."""
        if block_token is None:
            return None
        from ray_tpu._private.worker import global_runtime

        runtime = global_runtime()
        if runtime is None:
            return None
        return runtime.lookup_block_context(block_token)

    def get(self, keys: list[str], poll_s: float = 10.0,
            block_token: str | None = None,
            blocked_already: bool = False) -> tuple[str, bytes | None]:
        """Bounded server-side block: ("ok", values_blob) when every
        ref is ready within poll_s, else ("pending", None). The client
        loops — an RPC never outlives the socket timeout, so the
        transport's reconnect/resend cannot fire mid-long-get.

        CPU admission of the calling task is released on the FIRST poll
        and held released across "pending" rounds (blocked_already tells
        us a prior round blocked); it is restored only on a terminal
        outcome, so a long nested wait never thrashes acquire/release.
        """
        import ray_tpu

        refs = [self._resolve(k) for k in keys]
        ctx = self._block_ctx(block_token)
        if ctx is not None and not blocked_already:
            ctx.block()
        terminal = True
        try:
            ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=poll_s)
            if pending:
                terminal = False
                return ("pending", None)
            values = ray_tpu.get(refs)
            return ("ok", serialization.serialize_framed(values))
        finally:
            if ctx is not None and terminal:
                ctx.unblock(force=True)

    def wait(self, keys: list[str], num_returns: int,
             timeout: float | None, poll_s: float = 10.0,
             block_token: str | None = None,
             blocked_already: bool = False) -> tuple[list[str], list[str]]:
        """Server-side block capped at poll_s; the client loops."""
        import ray_tpu

        capped = poll_s if timeout is None else min(timeout, poll_s)
        refs = [self._resolve(k) for k in keys]
        ctx = self._block_ctx(block_token)
        if ctx is not None and not blocked_already:
            ctx.block()
        terminal = True
        try:
            ready, pending = ray_tpu.wait(
                refs, num_returns=num_returns, timeout=capped)
            terminal = (len(ready) >= num_returns
                        or (timeout is not None and timeout <= capped))
            by_ref = {id(r): k for r, k in zip(refs, keys)}
            return ([by_ref[id(r)] for r in ready],
                    [by_ref[id(r)] for r in pending])
        finally:
            if ctx is not None and terminal:
                ctx.unblock(force=True)

    def unblock(self, block_token: str) -> bool:
        """Client-side abandonment (timeout raised while a server-side
        block was held): restore the task's admission."""
        ctx = self._block_ctx(block_token)
        if ctx is None:
            return False
        ctx.drain()
        return True

    def disconnect_cleanup(self, ref_keys: list[str],
                           actor_keys: list[str],
                           borrower_id: str | None = None) -> int:
        """Release a disconnecting client's refs and kill its actors
        (reference: client session cleanup on connection close)."""
        n = self.release(ref_keys, borrower_id=borrower_id)
        for key in actor_keys:
            try:
                self.kill_actor(key)
            except Exception:  # noqa: BLE001 — already dead
                pass
        return n

    def task(self, func_blob: bytes, args_blob: bytes,
             options: dict, claimant: str | None = None) -> list[str]:
        import ray_tpu

        func = serialization.loads_function(func_blob)
        args, kwargs = self._deserialize_args(args_blob)
        remote_fn = ray_tpu.remote(func)
        if options:
            remote_fn = remote_fn.options(**options)
        out = remote_fn.remote(*args, **kwargs)
        refs = out if isinstance(out, (list, tuple)) else [out]
        return [self._track(r, claimant) for r in refs]

    def create_actor(self, cls_blob: bytes, args_blob: bytes,
                     options: dict) -> str:
        import ray_tpu

        cls = serialization.loads_function(cls_blob)
        args, kwargs = self._deserialize_args(args_blob)
        actor_cls = ray_tpu.remote(cls)
        if options:
            actor_cls = actor_cls.options(**options)
        handle = actor_cls.remote(*args, **kwargs)
        key = handle._actor_id.hex()
        with self._lock:
            self._actors[key] = handle
        return key

    def actor_call(self, actor_key: str, method: str,
                   args_blob: bytes, num_returns: int = 1,
                   claimant: str | None = None,
                   deadline_s: float | None = None) -> list[str]:
        handle = self._resolve_actor(actor_key)
        args, kwargs = self._deserialize_args(args_blob)
        bound = getattr(handle, method)
        if deadline_s is not None:
            bound = bound.options(num_returns=num_returns,
                                  _deadline_s=deadline_s)
        elif num_returns != 1:
            bound = bound.options(num_returns=num_returns)
        out = bound.remote(*args, **kwargs)
        refs = out if isinstance(out, (list, tuple)) else [out]
        return [self._track(r, claimant) for r in refs]

    def kill_actor(self, actor_key: str) -> bool:
        import ray_tpu

        with self._lock:
            handle = self._actors.pop(actor_key, None)
        if handle is None:
            try:
                handle = self._resolve_actor(actor_key)
            except KeyError:
                return False
        ray_tpu.kill(handle)
        return True

    def borrow(self, borrower_id: str, keys: list[str]) -> tuple:
        """A worker process deserialized these driver-owned refs and
        may hold them past its current task: pin them here (an
        ObjectRef registers a driver refcount, blocking eviction) until
        the borrower releases — or until its LEASE expires (borrow
        claims are leases refreshed by the worker's keepalive; a killed
        borrower's claims age out instead of pinning forever). Objects
        already gone simply don't pin — the borrower's eventual get()
        fails with the normal path."""
        import time as _time

        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_ref import ObjectRef
        from ray_tpu._private.worker import global_runtime

        runtime = global_runtime()
        pinned = 0
        now = _time.monotonic()
        for k in keys:
            oid = ObjectID(bytes.fromhex(k))
            exists = runtime is not None and (
                runtime.store.contains(oid)
                or runtime.store.is_pending(oid))
            # Whole per-key sequence under ONE lock hold: a concurrent
            # release must never interleave between the re-pin and the
            # borrower registration (it would leave a claimed key with
            # no pin). ObjectRef construction nests only the store
            # counter's leaf lock — safe under ours.
            with self._lock:
                have_pin = k in self._refs
                if not have_pin:
                    if not exists:
                        continue
                    self._refs[k] = ObjectRef(oid)
                    self._released.discard(k)
                self._borrowers.setdefault(k, set()).add(borrower_id)
                self._borrow_seen[(k, borrower_id)] = now
            pinned += 1
        # The TTL rides back so borrowers pace their keepalives against
        # THIS server's lease clock — the two processes need not share
        # a RAY_TPU_BORROW_TTL_S env var.
        return pinned, self._borrow_ttl_s

    def release(self, keys: list[str],
                borrower_id: str | None = None) -> int:
        with self._lock:
            n = 0
            for k in keys:
                holders = self._borrowers.get(k)
                if holders is not None:
                    holders.discard(borrower_id or "__direct__")
                    self._borrow_seen.pop((k, borrower_id), None)
                    if holders:
                        continue  # other holders keep the pin alive
                    self._borrowers.pop(k, None)
                if self._refs.pop(k, None) is not None:
                    n += 1
                    self._released.add(k)
            # Tombstones bound memory: keep only the most recent ones.
            if len(self._released) > 100_000:
                self._released = set(list(self._released)[-50_000:])
        return n

    def _sweep_expired_borrows(self) -> None:
        """Drop borrow leases whose keepalives stopped (borrower
        process died without releasing). Claimant pins from _track
        carry no lease — they are cleaned by release/disconnect."""
        import time as _time

        now = _time.monotonic()
        with self._lock:
            expired = [(k, bid) for (k, bid), seen
                       in self._borrow_seen.items()
                       if now - seen > self._borrow_ttl_s]
            for k, bid in expired:
                self._borrow_seen.pop((k, bid), None)
                holders = self._borrowers.get(k)
                if holders is None:
                    continue
                holders.discard(bid)
                if holders:
                    continue
                self._borrowers.pop(k, None)
                if self._refs.pop(k, None) is not None:
                    self._released.add(k)

    def _janitor_loop(self) -> None:
        while not self._stop.wait(min(5.0, self._borrow_ttl_s / 4)):
            try:
                self._sweep_expired_borrows()
            except Exception:  # noqa: BLE001 — janitor must survive
                pass

    def cancel(self, key: str) -> bool:
        import ray_tpu

        try:
            ray_tpu.cancel(self._resolve(key))
            return True
        except KeyError:
            return False

    def get_actor(self, name: str,
                  namespace: str | None = None) -> tuple[str, str]:
        import ray_tpu

        handle = ray_tpu.get_actor(name, namespace)
        key = handle._actor_id.hex()
        with self._lock:
            self._actors[key] = handle
        return key, handle._class_name

    def cluster_resources(self, available: bool = False) -> dict:
        import ray_tpu

        return (ray_tpu.available_resources() if available
                else ray_tpu.cluster_resources())
