"""Client server — hosts remote drivers against the local runtime.

Reference: python/ray/util/client/server/server.py (RayletServicer:
gRPC endpoints Schedule/GetObject/PutObject/WaitObject/Terminate
backed by the server-side ray worker). Here the endpoints ride the
framework RPC layer (rpc.py) and execute against this process's
Runtime (the head's, when embedded in the head daemon).

Object lifetime: every ref returned to a client is pinned in
``self._refs`` until the client disconnects or releases it, so the
runtime cannot GC results the client still names.
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu._private import serialization
from ray_tpu._private.rpc import RpcServer


class ClientServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._server = RpcServer(host, port)
        self._refs: dict[str, Any] = {}       # ref hex -> ObjectRef
        self._actors: dict[str, Any] = {}     # actor hex -> ActorHandle
        self._lock = threading.Lock()
        s = self._server
        s.register("ping", lambda: "pong")
        s.register("client_put", self.put)
        s.register("client_get", self.get)
        s.register("client_wait", self.wait)
        s.register("client_task", self.task)
        s.register("client_create_actor", self.create_actor)
        s.register("client_actor_call", self.actor_call)
        s.register("client_kill_actor", self.kill_actor)
        s.register("client_release", self.release)
        s.register("client_disconnect", self.disconnect_cleanup)

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def address(self) -> str:
        return self._server.address

    def start(self) -> "ClientServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop()

    # -- helpers ------------------------------------------------------
    def _track(self, ref) -> str:
        key = ref.id().hex()
        with self._lock:
            self._refs[key] = ref
        return key

    def _resolve(self, key: str):
        with self._lock:
            try:
                return self._refs[key]
            except KeyError:
                raise KeyError(f"unknown/released client ref {key}") \
                    from None

    def _deserialize_args(self, args_blob: bytes):
        args, kwargs = serialization.deserialize_from_buffer(
            memoryview(args_blob))

        def convert(v):
            # Symmetric with ClientAPI._marshal: placeholders may sit
            # inside lists/tuples/dicts, not just at the top level.
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "__ref__":
                return self._resolve(v[1])
            if isinstance(v, tuple) and len(v) == 2 \
                    and v[0] == "__actor__":
                with self._lock:
                    return self._actors[v[1]]
            # EXACT container types only: tuple/dict subclasses
            # (namedtuples, OrderedDicts) pass through untouched —
            # rebuilding them as plain containers would mangle them.
            if type(v) is list:
                return [convert(x) for x in v]
            if type(v) is tuple:
                return tuple(convert(x) for x in v)
            if type(v) is dict:
                return {k: convert(x) for k, x in v.items()}
            return v

        return (tuple(convert(a) for a in args),
                {k: convert(v) for k, v in kwargs.items()})

    # -- endpoints ----------------------------------------------------
    def put(self, value_blob: bytes) -> str:
        import ray_tpu

        value = serialization.deserialize_from_buffer(
            memoryview(value_blob))
        return self._track(ray_tpu.put(value))

    def get(self, keys: list[str],
            poll_s: float = 10.0) -> tuple[str, bytes | None]:
        """Bounded server-side block: ("ok", values_blob) when every
        ref is ready within poll_s, else ("pending", None). The client
        loops — an RPC never outlives the socket timeout, so the
        transport's reconnect/resend cannot fire mid-long-get.
        """
        import ray_tpu

        refs = [self._resolve(k) for k in keys]
        ready, pending = ray_tpu.wait(
            refs, num_returns=len(refs), timeout=poll_s)
        if pending:
            return ("pending", None)
        values = ray_tpu.get(refs)
        return ("ok", serialization.serialize_framed(values))

    def wait(self, keys: list[str], num_returns: int,
             timeout: float | None,
             poll_s: float = 10.0) -> tuple[list[str], list[str]]:
        """Server-side block capped at poll_s; the client loops."""
        import ray_tpu

        capped = poll_s if timeout is None else min(timeout, poll_s)
        refs = [self._resolve(k) for k in keys]
        ready, pending = ray_tpu.wait(
            refs, num_returns=num_returns, timeout=capped)
        by_ref = {id(r): k for r, k in zip(refs, keys)}
        return ([by_ref[id(r)] for r in ready],
                [by_ref[id(r)] for r in pending])

    def disconnect_cleanup(self, ref_keys: list[str],
                           actor_keys: list[str]) -> int:
        """Release a disconnecting client's refs and kill its actors
        (reference: client session cleanup on connection close)."""
        n = self.release(ref_keys)
        for key in actor_keys:
            try:
                self.kill_actor(key)
            except Exception:  # noqa: BLE001 — already dead
                pass
        return n

    def task(self, func_blob: bytes, args_blob: bytes,
             options: dict) -> list[str]:
        import ray_tpu

        func = serialization.loads_function(func_blob)
        args, kwargs = self._deserialize_args(args_blob)
        remote_fn = ray_tpu.remote(func)
        if options:
            remote_fn = remote_fn.options(**options)
        out = remote_fn.remote(*args, **kwargs)
        refs = out if isinstance(out, (list, tuple)) else [out]
        return [self._track(r) for r in refs]

    def create_actor(self, cls_blob: bytes, args_blob: bytes,
                     options: dict) -> str:
        import ray_tpu

        cls = serialization.loads_function(cls_blob)
        args, kwargs = self._deserialize_args(args_blob)
        actor_cls = ray_tpu.remote(cls)
        if options:
            actor_cls = actor_cls.options(**options)
        handle = actor_cls.remote(*args, **kwargs)
        key = handle._actor_id.hex()
        with self._lock:
            self._actors[key] = handle
        return key

    def actor_call(self, actor_key: str, method: str,
                   args_blob: bytes, num_returns: int = 1) -> list[str]:
        with self._lock:
            handle = self._actors[actor_key]
        args, kwargs = self._deserialize_args(args_blob)
        bound = getattr(handle, method)
        if num_returns != 1:
            bound = bound.options(num_returns=num_returns)
        out = bound.remote(*args, **kwargs)
        refs = out if isinstance(out, (list, tuple)) else [out]
        return [self._track(r) for r in refs]

    def kill_actor(self, actor_key: str) -> bool:
        import ray_tpu

        with self._lock:
            handle = self._actors.pop(actor_key, None)
        if handle is None:
            return False
        ray_tpu.kill(handle)
        return True

    def release(self, keys: list[str]) -> int:
        with self._lock:
            n = 0
            for k in keys:
                if self._refs.pop(k, None) is not None:
                    n += 1
        return n
