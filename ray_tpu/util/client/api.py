"""Client-side API — remote() / get() / put() proxied over RPC.

Reference: python/ray/util/client/__init__.py + worker.py (the client
worker that ships functions to the cluster and holds ClientObjectRefs).
"""

from __future__ import annotations

from typing import Any

from ray_tpu._private import serialization
from ray_tpu._private.rpc import RpcClient


class ClientObjectRef:
    def __init__(self, api: "ClientAPI", key: str):
        self._api = api
        self._key = key

    def __repr__(self):
        return f"ClientObjectRef({self._key[:12]})"


class ClientRemoteFunction:
    def __init__(self, api: "ClientAPI", func, options: dict | None = None):
        self._api = api
        self._func = func
        self._func_blob = serialization.dumps_function(func)
        self._options = dict(options or {})

    def options(self, **opts) -> "ClientRemoteFunction":
        return ClientRemoteFunction(
            self._api, self._func, {**self._options, **opts})

    def remote(self, *args, **kwargs):
        keys = self._api._rpc.call(
            "client_task", self._func_blob,
            self._api._marshal(args, kwargs), self._options)
        refs = [ClientObjectRef(self._api, k) for k in keys]
        return refs[0] if len(refs) == 1 else refs


class _ClientActorMethod:
    def __init__(self, api: "ClientAPI", actor_key: str, name: str):
        self._api = api
        self._actor_key = actor_key
        self._name = name
        self._num_returns = 1

    def options(self, *, num_returns: int = 1) -> "_ClientActorMethod":
        method = _ClientActorMethod(self._api, self._actor_key, self._name)
        method._num_returns = num_returns
        return method

    def remote(self, *args, **kwargs):
        keys = self._api._rpc.call(
            "client_actor_call", self._actor_key, self._name,
            self._api._marshal(args, kwargs), self._num_returns)
        refs = [ClientObjectRef(self._api, k) for k in keys]
        return refs[0] if len(refs) == 1 else refs


class ClientActorHandle:
    def __init__(self, api: "ClientAPI", actor_key: str):
        self._api = api
        self._actor_key = actor_key

    def __getattr__(self, name: str) -> _ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientActorMethod(self._api, self._actor_key, name)


class ClientRemoteClass:
    def __init__(self, api: "ClientAPI", cls, options: dict | None = None):
        self._api = api
        self._cls = cls
        self._cls_blob = serialization.dumps_function(cls)
        self._options = dict(options or {})

    def options(self, **opts) -> "ClientRemoteClass":
        return ClientRemoteClass(
            self._api, self._cls, {**self._options, **opts})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        key = self._api._rpc.call(
            "client_create_actor", self._cls_blob,
            self._api._marshal(args, kwargs), self._options)
        return ClientActorHandle(self._api, key)


class ClientAPI:
    """The remote() / get() / put() / wait() surface of a connected
    client (reference: ray.util.client ClientAPI)."""

    def __init__(self, address: str, timeout_s: float = 60.0):
        self._rpc = RpcClient(address, timeout_s=timeout_s)
        if not self._rpc.ping():
            raise ConnectionError(
                f"no ray_tpu client server at {address}")

    # -- marshalling --------------------------------------------------
    def _marshal(self, args: tuple, kwargs: dict) -> bytes:
        def convert(v):
            if isinstance(v, ClientObjectRef):
                return ("__ref__", v._key)
            if isinstance(v, ClientActorHandle):
                return ("__actor__", v._actor_key)
            return v

        return serialization.serialize_framed(
            (tuple(convert(a) for a in args),
             {k: convert(v) for k, v in kwargs.items()}))

    # -- API ----------------------------------------------------------
    def remote(self, func_or_class, **options):
        if isinstance(func_or_class, type):
            return ClientRemoteClass(self, func_or_class, options)
        return ClientRemoteFunction(self, func_or_class, options)

    def put(self, value: Any) -> ClientObjectRef:
        key = self._rpc.call(
            "client_put", serialization.serialize_framed(value))
        return ClientObjectRef(self, key)

    def get(self, refs, timeout: float | None = None):
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        blob = self._rpc.call(
            "client_get", [r._key for r in refs], timeout)
        values = serialization.deserialize_from_buffer(memoryview(blob))
        return values[0] if single else list(values)

    def wait(self, refs, *, num_returns: int = 1,
             timeout: float | None = None):
        by_key = {r._key: r for r in refs}
        ready, pending = self._rpc.call(
            "client_wait", [r._key for r in refs], num_returns, timeout)
        return ([by_key[k] for k in ready], [by_key[k] for k in pending])

    def kill(self, actor: ClientActorHandle) -> bool:
        return self._rpc.call("client_kill_actor", actor._actor_key)

    def release(self, refs) -> int:
        return self._rpc.call("client_release", [r._key for r in refs])

    def disconnect(self) -> None:
        self._rpc.close()


def connect(address: str, timeout_s: float = 60.0) -> ClientAPI:
    """Connect to a cluster's client server (reference:
    ray.init("ray://...") / ray.util.connect)."""
    return ClientAPI(address, timeout_s=timeout_s)
