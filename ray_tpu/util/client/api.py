"""Client-side API — remote() / get() / put() proxied over RPC.

Reference: python/ray/util/client/__init__.py + worker.py (the client
worker that ships functions to the cluster and holds ClientObjectRefs).
"""

from __future__ import annotations

from typing import Any

from ray_tpu._private import serialization
from ray_tpu._private.rpc import RpcClient


class ClientObjectRef:
    def __init__(self, api: "ClientAPI", key: str):
        self._api = api
        self._key = key

    def __repr__(self):
        return f"ClientObjectRef({self._key[:12]})"


class ClientRemoteFunction:
    def __init__(self, api: "ClientAPI", func, options: dict | None = None):
        self._api = api
        self._func = func
        self._func_blob = serialization.dumps_function(func)
        self._options = dict(options or {})

    def options(self, **opts) -> "ClientRemoteFunction":
        return ClientRemoteFunction(
            self._api, self._func, {**self._options, **opts})

    def remote(self, *args, **kwargs):
        keys = self._api._rpc.call(
            "client_task", self._func_blob,
            self._api._marshal(args, kwargs), self._options,
            claimant=self._api._borrower_id)
        refs = [self._api._new_ref(k) for k in keys]
        return refs[0] if len(refs) == 1 else refs


class _ClientActorMethod:
    def __init__(self, api: "ClientAPI", actor_key: str, name: str):
        self._api = api
        self._actor_key = actor_key
        self._name = name
        self._num_returns = 1

    def options(self, *, num_returns: int = 1) -> "_ClientActorMethod":
        method = _ClientActorMethod(self._api, self._actor_key, self._name)
        method._num_returns = num_returns
        return method

    def remote(self, *args, **kwargs):
        keys = self._api._rpc.call(
            "client_actor_call", self._actor_key, self._name,
            self._api._marshal(args, kwargs), self._num_returns,
            claimant=self._api._borrower_id)
        refs = [self._api._new_ref(k) for k in keys]
        return refs[0] if len(refs) == 1 else refs


class ClientActorHandle:
    def __init__(self, api: "ClientAPI", actor_key: str):
        self._api = api
        self._actor_key = actor_key

    def __getattr__(self, name: str) -> _ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientActorMethod(self._api, self._actor_key, name)


class ClientRemoteClass:
    def __init__(self, api: "ClientAPI", cls, options: dict | None = None):
        self._api = api
        self._cls = cls
        self._cls_blob = serialization.dumps_function(cls)
        self._options = dict(options or {})

    def options(self, **opts) -> "ClientRemoteClass":
        return ClientRemoteClass(
            self._api, self._cls, {**self._options, **opts})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        key = self._api._rpc.call(
            "client_create_actor", self._cls_blob,
            self._api._marshal(args, kwargs), self._options)
        self._api._live_actors.add(key)
        return ClientActorHandle(self._api, key)


class ClientAPI:
    """The remote() / get() / put() / wait() surface of a connected
    client (reference: ray.util.client ClientAPI)."""

    # Server-side poll window per RPC; must stay well under the socket
    # timeout so long gets never trip the transport's reconnect/resend.
    _POLL_S = 10.0

    def __init__(self, address: str, timeout_s: float = 60.0):
        import os as _os

        self._rpc = RpcClient(address, timeout_s=timeout_s)
        if not self._rpc.ping():
            raise ConnectionError(
                f"no ray_tpu client server at {address}")
        # Identity for the server's per-claimant pin accounting: this
        # session's releases can never drop another holder's pin.
        self._borrower_id = f"client-{_os.getpid()}-{_os.urandom(3).hex()}"
        # Session-owned server state, cleaned up on disconnect().
        self._live_refs: set[str] = set()
        self._live_actors: set[str] = set()

    # -- marshalling --------------------------------------------------
    def _marshal(self, args: tuple, kwargs: dict) -> bytes:
        def convert(v):
            # Recursive: refs inside lists/tuples/dicts must become
            # placeholders too (a raw ClientObjectRef drags its RpcClient
            # — socket + lock — into pickle and fails).
            if isinstance(v, ClientObjectRef):
                return ("__ref__", v._key)
            if isinstance(v, ClientActorHandle):
                return ("__actor__", v._actor_key)
            # EXACT container types only: tuple/dict subclasses
            # (namedtuples, OrderedDicts) pass through untouched —
            # rebuilding them as plain containers would mangle them.
            if type(v) is list:
                return [convert(x) for x in v]
            if type(v) is tuple:
                return tuple(convert(x) for x in v)
            if type(v) is dict:
                return {k: convert(x) for k, x in v.items()}
            return v

        return serialization.serialize_framed(
            (tuple(convert(a) for a in args),
             {k: convert(v) for k, v in kwargs.items()}))

    # -- API ----------------------------------------------------------
    def remote(self, func_or_class, **options):
        if isinstance(func_or_class, type):
            return ClientRemoteClass(self, func_or_class, options)
        return ClientRemoteFunction(self, func_or_class, options)

    def _new_ref(self, key: str) -> ClientObjectRef:
        self._live_refs.add(key)
        return ClientObjectRef(self, key)

    def put(self, value: Any) -> ClientObjectRef:
        key = self._rpc.call(
            "client_put", serialization.serialize_framed(value),
            claimant=self._borrower_id)
        return self._new_ref(key)

    def get(self, refs, timeout: float | None = None):
        """Chunked long-poll: each RPC blocks server-side at most
        _POLL_S, so tasks longer than the socket timeout still resolve
        (and the transport's resend can't duplicate a blocking get)."""
        import time as _time

        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        keys = [r._key for r in refs]
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        while True:
            # Poll window never exceeds the caller's remaining budget,
            # so get(timeout=0.5) returns in ~0.5s, not a full window.
            poll = self._POLL_S
            if deadline is not None:
                poll = min(poll, max(0.0, deadline - _time.monotonic()))
            status, blob = self._rpc.call("client_get", keys, poll)
            if status == "ok":
                values = serialization.deserialize_from_buffer(
                    memoryview(blob))
                return values[0] if single else list(values)
            if deadline is not None and _time.monotonic() >= deadline:
                raise TimeoutError(
                    f"client get timed out after {timeout}s")

    def wait(self, refs, *, num_returns: int = 1,
             timeout: float | None = None):
        import time as _time

        by_key = {r._key: r for r in refs}
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        while True:
            remaining = None if deadline is None \
                else max(0.0, deadline - _time.monotonic())
            ready, pending = self._rpc.call(
                "client_wait", [r._key for r in refs], num_returns,
                remaining, self._POLL_S)
            if len(ready) >= num_returns or (
                    remaining is not None and remaining <= 0):
                return ([by_key[k] for k in ready],
                        [by_key[k] for k in pending])

    def kill(self, actor: ClientActorHandle) -> bool:
        self._live_actors.discard(actor._actor_key)
        return self._rpc.call("client_kill_actor", actor._actor_key)

    def release(self, refs) -> int:
        keys = [r._key for r in refs]
        self._live_refs.difference_update(keys)
        return self._rpc.call("client_release", keys,
                              borrower_id=self._borrower_id)

    def disconnect(self) -> None:
        """Release this session's server-side refs and actors, then
        close. (A client that crashes without disconnecting leaves its
        refs pinned — same caveat as the reference client.)"""
        try:
            self._rpc.call("client_disconnect",
                           sorted(self._live_refs),
                           sorted(self._live_actors),
                           borrower_id=self._borrower_id)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass
        self._live_refs.clear()
        self._live_actors.clear()
        self._rpc.close()


def connect(address: str, timeout_s: float = 60.0) -> ClientAPI:
    """Connect to a cluster's client server (reference:
    ray.init("ray://...") / ray.util.connect)."""
    return ClientAPI(address, timeout_s=timeout_s)
