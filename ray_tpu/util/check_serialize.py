"""inspect_serializability — find WHY an object will not pickle.

Reference: python/ray/util/check_serialize.py (walks closures and
attributes of a failing object, printing a tree of the unserializable
leaves).
"""

from __future__ import annotations

import inspect
from typing import Any

from ray_tpu._private import serialization


class FailureTuple:
    """One unserializable leaf: the object, its name, and its parent."""

    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple(obj={self.obj!r}, name={self.name!r})"


def _serializable(obj: Any) -> bool:
    try:
        serialization.dumps_function(obj)
        return True
    except Exception:  # noqa: BLE001
        return False


def _find_failures(obj: Any, name: str, parent: Any, found: list,
                   seen: set, depth: int = 0) -> None:
    if id(obj) in seen or depth > 4:
        return
    seen.add(id(obj))
    if _serializable(obj):
        return
    children: list[tuple[str, Any]] = []
    if inspect.isfunction(obj):
        # Closure cells + globals the function references.
        if obj.__closure__:
            for var, cell in zip(obj.__code__.co_freevars,
                                 obj.__closure__):
                try:
                    children.append((var, cell.cell_contents))
                except ValueError:
                    pass
        for gname in obj.__code__.co_names:
            if gname in obj.__globals__:
                children.append((gname, obj.__globals__[gname]))
    elif hasattr(obj, "__dict__") and isinstance(obj.__dict__, dict):
        children.extend(obj.__dict__.items())

    child_failures_before = len(found)
    for cname, child in children:
        if not _serializable(child):
            _find_failures(child, cname, obj, found, seen, depth + 1)
    if len(found) == child_failures_before:
        # No deeper culprit: this object itself is the leaf.
        found.append(FailureTuple(obj, name, parent))


def inspect_serializability(
        obj: Any, name: str | None = None
) -> tuple[bool, list[FailureTuple]]:
    """-> (is_serializable, failure_leaves). Reference:
    check_serialize.inspect_serializability."""
    name = name or getattr(obj, "__name__", repr(obj)[:40])
    if _serializable(obj):
        return True, []
    found: list[FailureTuple] = []
    _find_failures(obj, name, None, found, set())
    return False, found
