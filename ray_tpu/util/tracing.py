"""Span tracing — OpenTelemetry-shaped spans over runtime activity.

Reference: python/ray/util/tracing/ (tracing_helper.py:36 instruments
task submit/execute with OTel spans; enabled via `ray.init(_tracing_...)`
and exported by a user-provided exporter). Here the tracer is built in:

- ``enable()`` starts collecting; user code opens spans with
  ``with trace_span("name"):`` (nesting gives parent/child links via a
  contextvar, which propagates correctly across threads the runtime
  starts per actor/task);
- task submission/execution is traced automatically from the GCS task
  events the runtime already records (no double instrumentation);
- ``export_chrome_trace(path)`` writes everything — user spans + task
  events — as one chrome://tracing / Perfetto JSON file;
  ``get_spans()`` returns structured spans for programmatic use.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Iterator

_current_span: contextvars.ContextVar["Span | None"] = \
    contextvars.ContextVar("ray_tpu_current_span", default=None)


@dataclass
class Span:
    name: str
    span_id: str
    parent_id: str | None
    start_time: float
    end_time: float | None = None
    attributes: dict = field(default_factory=dict)
    thread: str = ""

    def duration_s(self) -> float | None:
        if self.end_time is None:
            return None
        return self.end_time - self.start_time


class _Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self.enabled = False

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


_TRACER = _Tracer()


def enable() -> None:
    """Start collecting spans (reference: tracing startup hook)."""
    _TRACER.enabled = True


def disable() -> None:
    _TRACER.enabled = False


def is_enabled() -> bool:
    return _TRACER.enabled


def clear() -> None:
    _TRACER.clear()


@contextlib.contextmanager
def trace_span(name: str, attributes: dict | None = None) -> Iterator[Span]:
    """Open a span; nests under the current span in this context."""
    parent = _current_span.get()
    span = Span(
        name=name,
        span_id=uuid.uuid4().hex[:16],
        parent_id=parent.span_id if parent else None,
        start_time=time.time(),
        attributes=dict(attributes or {}),
        thread=threading.current_thread().name,
    )
    token = _current_span.set(span)
    try:
        yield span
    except BaseException as exc:
        span.attributes["error"] = f"{type(exc).__name__}: {exc}"
        raise
    finally:
        span.end_time = time.time()
        _current_span.reset(token)
        if _TRACER.enabled:
            _TRACER.record(span)


def get_current_span() -> Span | None:
    return _current_span.get()


def get_spans() -> list[Span]:
    """All completed spans collected since enable()/clear()."""
    return _TRACER.spans()


def export_chrome_trace(path: str) -> int:
    """Write user spans + runtime task events as one chrome trace.

    Returns the number of events written. Open in chrome://tracing or
    https://ui.perfetto.dev.
    """
    from ray_tpu._private.worker import global_runtime

    events: list[dict] = []
    for span in _TRACER.spans():
        if span.end_time is None:
            continue
        events.append({
            "name": span.name,
            "cat": "span",
            "ph": "X",
            "ts": span.start_time * 1e6,
            "dur": (span.end_time - span.start_time) * 1e6,
            "pid": 0,
            "tid": span.thread or "main",
            "args": {**span.attributes,
                     "span_id": span.span_id,
                     "parent_id": span.parent_id},
        })
    runtime = global_runtime()
    if runtime is not None:
        for ev in runtime.gcs.list_task_events():
            if not ev.start_time or not ev.end_time:
                continue
            events.append({
                "name": ev.name,
                "cat": "task",
                "ph": "X",
                "ts": ev.start_time * 1e6,
                "dur": max(ev.end_time - ev.start_time, 1e-6) * 1e6,
                "pid": 1,
                "tid": "tasks",
                "args": {"task_id": ev.task_id.hex(),
                         "state": ev.state},
            })
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)
