"""Distributed span tracing — OpenTelemetry-shaped spans over runtime
activity, with cross-process trace-context propagation.

Reference: python/ray/util/tracing/ (tracing_helper.py:36 instruments
task submit/execute with OTel spans) and the gcs_task_manager task-event
subsystem behind ``ray timeline``. Here the tracer is built in:

- ``enable()`` starts collecting; user code opens spans with
  ``with trace_span("name"):`` (nesting gives parent/child links via a
  contextvar, which propagates correctly across threads the runtime
  starts per actor/task);
- task submission/execution is traced automatically: the driver stamps
  a compact trace context ``(trace_id, parent span_id, anchor)`` onto
  every task submit; the context rides the ``execute_task`` /
  ``execute_task_batch`` RPCs and the worker pipe ``task_seq`` frames,
  so spans opened in daemons and pool workers link back to the
  driver-side submit span. Remote spans are buffered locally
  (``buffer_span``) and shipped back piggybacked on existing reply
  frames and heartbeats — no new chatty RPCs;
- per-process clock skew is corrected driver-side: every trace payload
  carries the remote wall clock at send, and ``ClockSync`` keeps the
  minimum-RTT half-RTT offset estimate per peer so merged timelines
  line up;
- ``export_chrome_trace(path)`` writes everything — user spans, remote
  spans, per-stage task lifecycles, fault/chaos instants, and flow
  arrows from submit→execute→seal — as one chrome://tracing / Perfetto
  JSON file with one process lane per node/worker; ``get_spans()``
  returns structured spans for programmatic use.

Cost discipline: when tracing is disabled every instrumentation site
pays one module-attribute branch (``if tracing.TRACE_ON:``) — the same
contract as ``chaos.ACTIVE``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Iterator

_current_span: contextvars.ContextVar["Span | None"] = \
    contextvars.ContextVar("ray_tpu_current_span", default=None)

# The ONE production branch: instrumentation sites across the runtime
# (scheduler claim, RPC retry, chaos firings, worker frames) check this
# module attribute and pay nothing else while tracing is off.
TRACE_ON: bool = False

# Canonical pipeline stage order (TaskEvent.stage_ts keys), driver
# clock after offset correction. Used by the exporter to slice a task's
# lifecycle and by tests asserting monotonic ordering.
STAGES = ("submit", "dispatch", "rpc_sent", "admitted", "worker_start",
          "exec_start", "exec_end", "seal")


@dataclass
class Span:
    name: str
    span_id: str
    parent_id: str | None
    start_time: float
    end_time: float | None = None
    attributes: dict = field(default_factory=dict)
    thread: str = ""
    trace_id: str = ""
    # Process lane label ("driver", "node:<tag>", "worker:<pid>") for
    # the merged timeline; empty = this process.
    proc: str = ""

    def duration_s(self) -> float | None:
        if self.end_time is None:
            return None
        return self.end_time - self.start_time


def _buffer_cap() -> int:
    try:
        from ray_tpu._private.config import GLOBAL_CONFIG

        return max(1, int(GLOBAL_CONFIG.tracing_buffer_max_spans))
    except Exception:  # noqa: BLE001 — config unavailable mid-teardown
        return 4096


class _Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        # Remote-shipping buffer (daemon/worker side): span dicts
        # waiting to piggyback on the next reply frame / heartbeat.
        self._outbox: list[dict] = []
        self.dropped = 0
        self.enabled = False

    def record(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) >= _buffer_cap():
                self.dropped += 1
                return
            self._spans.append(span)

    def buffer(self, span_dict: dict) -> None:
        with self._lock:
            if len(self._outbox) >= _buffer_cap():
                self.dropped += 1
                return
            self._outbox.append(span_dict)

    def drain(self) -> list[dict]:
        with self._lock:
            out, self._outbox = self._outbox, []
            return out

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._outbox.clear()
            self.dropped = 0


_TRACER = _Tracer()


def enable() -> None:
    """Start collecting spans (reference: tracing startup hook)."""
    global TRACE_ON
    _TRACER.enabled = True
    TRACE_ON = True


def disable() -> None:
    global TRACE_ON
    _TRACER.enabled = False
    TRACE_ON = False


def is_enabled() -> bool:
    return _TRACER.enabled


def clear() -> None:
    _TRACER.clear()


def dropped_spans() -> int:
    """Spans discarded because a buffer hit tracing_buffer_max_spans."""
    return _TRACER.dropped


@contextlib.contextmanager
def trace_span(name: str, attributes: dict | None = None) -> Iterator[Span]:
    """Open a span; nests under the current span in this context."""
    parent = _current_span.get()
    span = Span(
        name=name,
        span_id=uuid.uuid4().hex[:16],
        parent_id=parent.span_id if parent else None,
        start_time=time.time(),
        attributes=dict(attributes or {}),
        thread=threading.current_thread().name,
        trace_id=(parent.trace_id if parent and parent.trace_id
                  else uuid.uuid4().hex[:16]),
    )
    token = _current_span.set(span)
    try:
        yield span
    except BaseException as exc:
        span.attributes["error"] = f"{type(exc).__name__}: {exc}"
        raise
    finally:
        span.end_time = time.time()
        _current_span.reset(token)
        if _TRACER.enabled:
            _TRACER.record(span)


def get_current_span() -> Span | None:
    return _current_span.get()


def get_spans() -> list[Span]:
    """All completed spans collected since enable()/clear()."""
    return _TRACER.spans()


# --------------------------------------------------------------------------
# Cross-process trace context
# --------------------------------------------------------------------------
#
# A trace context is a compact picklable tuple riding the existing RPCs:
#     (trace_id, parent_span_id, anchor)
# ``anchor`` is the originating driver's wall clock at creation — remote
# processes never use it for arithmetic directly (skew!), it only tags
# the context's origin for debugging; real merge correction comes from
# ClockSync half-RTT estimation on the reply path.


def make_trace_context(name: str | None = None,
                       anchor: float | None = None) -> tuple | None:
    """Context for an outgoing task submit: links to the current span
    when one is open, else roots a fresh trace. None when disabled —
    the absence of a context IS the cross-process disable signal (the
    remote side never needs its own tracing flag for runtime spans)."""
    if not TRACE_ON:
        return None
    parent = _current_span.get()
    if parent is not None:
        trace_id = parent.trace_id or uuid.uuid4().hex[:16]
        parent_id = parent.span_id
    else:
        trace_id = uuid.uuid4().hex[:16]
        parent_id = None
    return (trace_id, parent_id, anchor if anchor is not None
            else time.time())


@contextlib.contextmanager
def remote_span(name: str, ctx: tuple | None, proc: str,
                attributes: dict | None = None) -> Iterator[dict]:
    """Daemon/worker-side span linked to a driver trace context.

    The span is recorded as a plain dict into the local outbox
    (``drain_buffered``) so it ships back piggybacked on the next reply
    frame or heartbeat. Timestamps are THIS process's wall clock; the
    driver corrects them with its ClockSync offset at ingest."""
    span = {
        "name": name,
        "span_id": uuid.uuid4().hex[:16],
        "parent_id": ctx[1] if ctx else None,
        "trace_id": ctx[0] if ctx else uuid.uuid4().hex[:16],
        "start_time": time.time(),
        "end_time": None,
        "thread": threading.current_thread().name,
        "proc": proc,
        "attributes": dict(attributes or {}),
    }
    try:
        yield span
    except BaseException as exc:
        span["attributes"]["error"] = f"{type(exc).__name__}: {exc}"
        raise
    finally:
        span["end_time"] = time.time()
        _TRACER.buffer(span)


def buffer_span(span_dict: dict) -> None:
    """Queue one remote span dict for piggyback shipping."""
    _TRACER.buffer(span_dict)


def drain_buffered() -> list[dict]:
    """Pop every span queued for shipping (reply-frame/heartbeat
    piggyback). Returns [] when nothing is buffered — callers attach
    the payload only when non-empty."""
    return _TRACER.drain()


def ingest_spans(span_dicts: list[dict], offset_s: float = 0.0) -> int:
    """Driver-side merge of remote spans: apply the peer's clock offset
    (remote ts + offset ≈ driver ts) and record them as first-class
    spans. Returns the number ingested."""
    n = 0
    for d in span_dicts:
        try:
            end = d.get("end_time")
            span = Span(
                name=d["name"],
                span_id=d.get("span_id", uuid.uuid4().hex[:16]),
                parent_id=d.get("parent_id"),
                start_time=float(d["start_time"]) + offset_s,
                end_time=(float(end) + offset_s) if end else None,
                attributes=dict(d.get("attributes") or {}),
                thread=d.get("thread", ""),
                trace_id=d.get("trace_id", ""),
                proc=d.get("proc", ""),
            )
        except (KeyError, TypeError, ValueError):
            continue  # malformed remote span: skip, never poison merge
        _TRACER.record(span)
        n += 1
    return n


def instant(name: str, attributes: dict | None = None,
            proc: str = "") -> None:
    """Record a zero-duration instant event (fault counters, chaos
    firings). Shown as an 'i' pin in the merged timeline. Callers
    gate on ``tracing.TRACE_ON`` so the disabled cost is one branch."""
    if not TRACE_ON:
        return
    span = Span(
        name=name,
        span_id=uuid.uuid4().hex[:16],
        parent_id=None,
        start_time=time.time(),
        end_time=None,
        attributes={**(attributes or {}), "instant": True},
        thread=threading.current_thread().name,
        proc=proc,
    )
    _TRACER.record(span)


def buffer_instant(name: str, proc: str,
                   attributes: dict | None = None) -> None:
    """Remote-process variant of ``instant``: queued for piggyback
    shipping instead of recorded locally."""
    if not TRACE_ON:
        return
    _TRACER.buffer({
        "name": name,
        "span_id": uuid.uuid4().hex[:16],
        "parent_id": None,
        "trace_id": "",
        "start_time": time.time(),
        "end_time": None,
        "thread": threading.current_thread().name,
        "proc": proc,
        "attributes": {**(attributes or {}), "instant": True},
    })


class ClockSync:
    """Per-peer monotonic→driver-clock offset estimation.

    Classic NTP four-timestamp anchoring on existing exchanges (lease
    replies, heartbeats): t0 = local request send, t1 = peer receive
    (the daemon's admission stamp), t2 = peer reply send (the trace
    payload's ``now``), t3 = local reply receive. Server processing
    time (t2−t1) subtracts out of the RTT, so a long-running task
    cannot bias the estimate; the minimum-RTT sample wins — it bounds
    the path-asymmetry error the tightest. ``offset`` is defined so
    that ``driver_time ≈ remote_time + offset``."""

    __slots__ = ("offset", "rtt", "samples", "_lock")

    def __init__(self):
        self.offset = 0.0
        self.rtt = float("inf")
        self.samples = 0
        self._lock = threading.Lock()

    def observe(self, t_send: float, t_recv: float,
                remote_ts: float,
                remote_recv_ts: float | None = None) -> float:
        """One exchange; ``remote_recv_ts`` (t1) defaults to
        ``remote_ts`` (t2) — the degenerate half-RTT form for replies
        that carry only one peer stamp. Returns the current best
        offset."""
        if remote_recv_ts is None:
            remote_recv_ts = remote_ts
        rtt = max(0.0, (t_recv - t_send) - (remote_ts - remote_recv_ts))
        # NTP: θ = ((t1−t0)+(t2−t3))/2 is remote−local; negate for the
        # remote→driver correction.
        offset = -(((remote_recv_ts - t_send)
                    + (remote_ts - t_recv)) / 2.0)
        with self._lock:
            self.samples += 1
            if rtt <= self.rtt:
                self.rtt = rtt
                self.offset = offset
            return self.offset


# --------------------------------------------------------------------------
# Merged timeline export
# --------------------------------------------------------------------------


class _LaneTable:
    """Stable integer pid/tid assignment per process/thread label, plus
    the 'M' metadata events Perfetto needs to group and name lanes
    (string tids violate the chrome trace format and scatter events)."""

    def __init__(self):
        self._pids: dict[str, int] = {}
        self._tids: dict[tuple[int, str], int] = {}
        self.meta: list[dict] = []

    def pid(self, proc: str) -> int:
        proc = proc or "driver"
        got = self._pids.get(proc)
        if got is None:
            got = len(self._pids) + 1
            self._pids[proc] = got
            self.meta.append({
                "name": "process_name", "ph": "M", "pid": got, "tid": 0,
                "args": {"name": proc}})
            self.meta.append({
                "name": "process_sort_index", "ph": "M", "pid": got,
                "tid": 0, "args": {"sort_index": got}})
        return got

    def tid(self, pid: int, thread: str) -> int:
        thread = thread or "main"
        key = (pid, thread)
        got = self._tids.get(key)
        if got is None:
            got = sum(1 for (p, _t) in self._tids if p == pid) + 1
            self._tids[key] = got
            self.meta.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": got,
                "args": {"name": thread}})
        return got


# Stage slice layout for one task: (slice name, from stage, to stage,
# lane). "remote" lanes land in the executing node's process lane.
_STAGE_SLICES = (
    ("stage:submit→dispatch", "submit", "dispatch", "driver"),
    ("stage:dispatch→rpc", "dispatch", "rpc_sent", "driver"),
    ("stage:rpc→admit", "rpc_sent", "admitted", "remote"),
    ("stage:admit→worker", "admitted", "worker_start", "remote"),
    ("stage:worker→exec", "worker_start", "exec_start", "remote"),
    ("stage:execute", "exec_start", "exec_end", "remote"),
    ("stage:exec→seal", "exec_end", "seal", "driver"),
)


def _task_lane(ev) -> str:
    return f"node:{ev.node_id[:8]}" if ev.node_id else "driver"


def build_task_events(runtime, lanes: "_LaneTable | None" = None
                      ) -> list[dict]:
    """Chrome-trace events for the runtime's task lifecycle records:
    per-stage slices (one lane per node) with flow arrows linking the
    driver-side submit to the remote execution and back to the seal.
    Tasks without stage stamps degrade to the single-slice view."""
    own_lanes = lanes is None
    if own_lanes:
        lanes = _LaneTable()
    events: list[dict] = []
    for ev in runtime.gcs.list_task_events():
        stage_ts = getattr(ev, "stage_ts", None) or {}
        present = [s for s in STAGES if s in stage_ts]
        if len(present) >= 2:
            flow_id = ev.task_id.hex()
            prev_lane = None
            for name, a, b, lane_kind in _STAGE_SLICES:
                if a not in stage_ts or b not in stage_ts:
                    continue
                lane = ("driver" if lane_kind == "driver"
                        else _task_lane(ev))
                pid = lanes.pid(lane)
                tid = lanes.tid(pid, "tasks")
                ts = stage_ts[a] * 1e6
                events.append({
                    "name": f"{ev.name} {name}", "cat": "task_stage",
                    "ph": "X", "ts": ts,
                    "dur": max((stage_ts[b] - stage_ts[a]) * 1e6, 1.0),
                    "pid": pid, "tid": tid,
                    "args": {"task_id": flow_id, "state": ev.state},
                })
                if prev_lane is not None and prev_lane != lane:
                    # Cross-lane hop: a flow arrow from the end of the
                    # previous slice to the start of this one.
                    prev_pid = lanes.pid(prev_lane)
                    events.append({
                        "name": "task_flow", "cat": "task_flow",
                        "ph": "s", "id": flow_id, "ts": ts - 1.0,
                        "pid": prev_pid,
                        "tid": lanes.tid(prev_pid, "tasks")})
                    events.append({
                        "name": "task_flow", "cat": "task_flow",
                        "ph": "f", "bp": "e", "id": flow_id, "ts": ts,
                        "pid": pid, "tid": tid})
                prev_lane = lane
            continue
        if not ev.start_time or not ev.end_time:
            continue
        pid = lanes.pid(_task_lane(ev))
        tid = lanes.tid(pid, "tasks")
        events.append({
            "name": ev.name, "cat": "task", "ph": "X",
            "ts": ev.start_time * 1e6,
            "dur": max(ev.end_time - ev.start_time, 1e-6) * 1e6,
            "pid": pid, "tid": tid,
            "args": {"task_id": ev.task_id.hex(), "state": ev.state},
        })
    if own_lanes:
        return lanes.meta + events
    return events


def _span_events(lanes: _LaneTable) -> list[dict]:
    events: list[dict] = []
    for span in _TRACER.spans():
        pid = lanes.pid(span.proc or "driver")
        tid = lanes.tid(pid, span.thread or "main")
        if span.attributes.get("instant") or span.end_time is None:
            events.append({
                "name": span.name,
                "cat": "fault" if span.name.startswith(
                    ("fault:", "chaos:")) else "instant",
                "ph": "i", "s": "p",
                "ts": span.start_time * 1e6,
                "pid": pid, "tid": tid,
                "args": {**span.attributes, "span_id": span.span_id},
            })
            continue
        events.append({
            "name": span.name,
            "cat": "span",
            "ph": "X",
            "ts": span.start_time * 1e6,
            "dur": max(span.end_time - span.start_time, 1e-6) * 1e6,
            "pid": pid, "tid": tid,
            "args": {**span.attributes,
                     "span_id": span.span_id,
                     "trace_id": span.trace_id,
                     "parent_id": span.parent_id},
        })
    return events


def _drain_cluster_spans(runtime) -> None:
    """Pull daemon spans that shipped to the head on heartbeats (the
    piggyback fallback for spans no reply frame carried) into the local
    tracer before exporting. Offsets here are one-way heartbeat
    estimates — coarser than the half-RTT reply path, but these spans
    had no reply to anchor on."""
    if runtime is None or runtime.gcs_client is None:
        return
    try:
        batches = runtime.gcs_client.call("drain_trace_spans",
                                          timeout_s=5.0)
    except Exception:  # noqa: BLE001 — head unreachable: local view only
        return
    for entry in batches or []:
        try:
            spans, offset = entry["spans"], float(entry.get("offset", 0.0))
        except (TypeError, KeyError):
            continue
        ingest_spans(spans, offset)


def export_chrome_trace(path: str) -> int:
    """Write user spans + remote spans + per-stage task lifecycles as
    one merged chrome trace (integer pid/tid + process_name metadata —
    Perfetto groups one lane per node/worker process).

    Returns the number of events written. Open in chrome://tracing or
    https://ui.perfetto.dev.
    """
    from ray_tpu._private.worker import global_runtime

    runtime = global_runtime()
    _drain_cluster_spans(runtime)
    lanes = _LaneTable()
    events = _span_events(lanes)
    if runtime is not None:
        events += build_task_events(runtime, lanes)
    events = lanes.meta + events
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)
