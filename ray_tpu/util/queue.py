"""Distributed Queue backed by an actor.

Reference: python/ray/util/queue.py (Queue wrapping a _QueueActor;
blocking put/get with timeouts, Empty/Full mirroring queue module
semantics).
"""

from __future__ import annotations

import time
from typing import Any

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        import collections

        self.maxsize = maxsize
        self._items: collections.deque = collections.deque()

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self._items)

    def put_nowait(self, item: Any) -> bool:
        if self.full():
            return False
        self._items.append(item)
        return True

    def put_nowait_batch(self, items: list) -> bool:
        if self.maxsize and len(self._items) + len(items) > self.maxsize:
            return False
        self._items.extend(items)
        return True

    def get_nowait(self):
        if not self._items:
            return False, None
        return True, self._items.popleft()

    def get_nowait_batch(self, num_items: int):
        if len(self._items) < num_items:
            return False, None
        return True, [self._items.popleft() for _ in range(num_items)]


class Queue:
    """Cluster-visible FIFO queue; handles are shareable across tasks
    and actors like any ActorHandle."""

    def __init__(self, maxsize: int = 0, actor_options: dict | None = None):
        self.maxsize = maxsize
        options = actor_options or {}
        self.actor = ray_tpu.remote(_QueueActor).options(
            **options).remote(maxsize)

    def __getstate__(self):
        return {"maxsize": self.maxsize, "actor": self.actor}

    def __setstate__(self, state):
        self.maxsize = state["maxsize"]
        self.actor = state["actor"]

    # -- inspection ---------------------------------------------------
    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    # -- put/get ------------------------------------------------------
    def put(self, item: Any, block: bool = True,
            timeout: float | None = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put_nowait.remote(item)):
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: list) -> None:
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(
                list(items))):
            raise Full

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty
            return item
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if ok:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty
            time.sleep(0.01)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> list:
        ok, items = ray_tpu.get(
            self.actor.get_nowait_batch.remote(num_items))
        if not ok:
            raise Empty
        return items

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
