import sys

from ray_tpu.util.state.api import _cli

sys.exit(_cli(sys.argv[1:]))
