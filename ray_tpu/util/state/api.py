"""State API implementation (reference: python/ray/util/state/api.py).

Every listing returns plain dicts, newest-first where a time exists,
with reference-style filters: ``filters=[("state", "=", "FAILED")]``
supports ``=``/``!=``, and ``limit`` caps the result size.
"""

from __future__ import annotations

import logging
from typing import Any

from ray_tpu._private import worker as worker_mod

logger = logging.getLogger("ray_tpu")


def _runtime():
    runtime = worker_mod.global_runtime()
    if runtime is None:
        raise RuntimeError("ray_tpu is not initialized")
    return runtime


class ListResult(list):
    """A listing that KNOWS it was capped: ``truncated`` is True when
    ``limit`` dropped rows and ``total`` is the pre-cap match count
    (reference: the state API's NUM_AFTER_TRUNCATION warning — a
    silently capped list reads as 'that's everything' otherwise).
    Serializes as a plain JSON list; the dashboard surfaces the flag
    as an X-Ray-Tpu-Truncated response header."""

    truncated: bool = False
    total: int = 0


def _apply_filters(rows: list[dict], filters, limit: int) -> ListResult:
    for key, op, value in (filters or []):
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"Unsupported filter op {op!r}; use '=' or '!='")
    out = ListResult(rows[:limit])
    out.total = len(rows)
    out.truncated = len(rows) > limit
    if out.truncated:
        logger.warning(
            "state listing truncated: %d of %d rows returned "
            "(raise limit= to see the rest)", limit, len(rows))
    return out


# ------------------------------------------------------------------- tasks


def list_tasks(filters=None, limit: int = 100) -> list[dict]:
    """Reference: `ray list tasks` (api.py:1014)."""
    events = _runtime().gcs.list_task_events()
    rows = [
        {
            "task_id": ev.task_id.hex(),
            "name": ev.name,
            "state": ev.state,
            "node_id": ev.node_id,
            "start_time": ev.start_time,
            "end_time": ev.end_time,
            "error": ev.error,
        }
        for ev in events
    ]
    rows.sort(key=lambda r: r["start_time"], reverse=True)
    return _apply_filters(rows, filters, limit)


def get_task(task_id: str) -> dict | None:
    for row in list_tasks(limit=10**9):
        if row["task_id"] == task_id:
            return row
    return None


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile of a pre-sorted sample."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _node_stats_table(runtime) -> dict:
    """The GCS node-stats aggregation table (with receipt ages),
    fetched once per call."""
    client = getattr(runtime, "gcs_client", None)
    if client is not None:
        try:
            return client.call("node_stats", timeout_s=2.0) or {}
        except Exception:  # noqa: BLE001 — head unreachable: local view
            return {}
    return runtime.gcs.node_stats()


def _cluster_task_resources(runtime) -> dict:
    """Per-function attribution merged across the cluster: this
    driver's table + every node's heartbeat-shipped snapshot from the
    GCS aggregation table."""
    from ray_tpu._private import perf_plane

    merged: dict[str, dict] = {}
    perf_plane.merge_resource_tables(
        merged, perf_plane.resource_snapshot())
    for stats in _node_stats_table(runtime).values():
        if isinstance(stats, dict):
            perf_plane.merge_resource_tables(
                merged, stats.get("task_resources") or {})
    return merged


def summarize_placement() -> dict:
    """Per-node placement/load table + the driver's scheduler decision
    counters (the view `python -m ray_tpu summary` prints alongside
    the task summary): for each node its admitted-reservation
    ``depth`` / ``running``, the stats feed's receipt ``age_s`` (stale
    past ``sched_stats_stale_s`` = decayed out of the load score),
    executed-task count and the heartbeat-shipped ``admit_p50_ms`` /
    ``exec_p50_ms``; plus ``decisions`` — locality hits / bytes saved,
    load spillbacks, stale-stats skips and speculation outcomes."""
    from ray_tpu._private import perf_plane

    runtime = _runtime()
    nodes: dict[str, dict] = {}
    for node_hex, stats in sorted(_node_stats_table(runtime).items()):
        if not isinstance(stats, dict):
            continue
        hist = stats.get("stage_hist") \
            if isinstance(stats.get("stage_hist"), dict) else {}
        nodes[node_hex[:16]] = {
            "running": stats.get("running", 0),
            "depth": stats.get("depth", stats.get("running", 0)),
            "tasks_executed": stats.get("tasks_executed", 0),
            "age_s": round(float(stats.get("age_s", 0.0) or 0.0), 3),
            "admit_p50_ms": round(perf_plane.quantile(
                hist.get("admit_worker") or {}, 0.5) * 1e3, 3),
            "exec_p50_ms": round(perf_plane.quantile(
                hist.get("exec") or {}, 0.5) * 1e3, 3),
        }
    decisions = runtime.execution_pipeline_stats().get("sched", {})
    return {"nodes": nodes, "decisions": decisions}


def summarize_tasks() -> dict:
    """Counts by (name, state), plus the always-on performance plane's
    per-function views (reference: summarize_tasks api.py:1376 and the
    per-stage task latency summaries):

    - ``latency``: wall-clock count/mean/p50/p95/p99 per function from
      the task-event table (exact sample percentiles — recorded with
      tracing disabled);
    - ``resources``: cpu-seconds / wall / peak-RSS attribution per
      function signature, merged across the driver and every node.
    """
    summary: dict[str, dict[str, int]] = {}
    durations: dict[str, list] = {}
    runtime = _runtime()
    for ev in runtime.gcs.list_task_events():
        per_name = summary.setdefault(ev.name, {})
        per_name[ev.state] = per_name.get(ev.state, 0) + 1
        if ev.state == "FINISHED" and ev.end_time and ev.start_time:
            durations.setdefault(ev.name, []).append(
                ev.end_time - ev.start_time)
    latency: dict[str, dict] = {}
    for name, vals in durations.items():
        vals.sort()
        latency[name] = {
            "count": len(vals),
            "mean_s": sum(vals) / len(vals),
            "p50_s": _percentile(vals, 0.50),
            "p95_s": _percentile(vals, 0.95),
            "p99_s": _percentile(vals, 0.99),
        }
    pipeline = runtime.execution_pipeline_stats()
    return {"node_count": len(list_nodes(limit=10**9)),
            "summary": summary,
            "latency": latency,
            "resources": _cluster_task_resources(runtime),
            # Placement/load table + scheduler decision counters: the
            # default `ray_tpu summary` view shows WHERE work landed
            # and why (locality hits, load spillbacks, speculation).
            "placement": summarize_placement(),
            # Driver submit/dispatch hot-path counters (ISSUE 15):
            # ring + columnar intake, flush latency, lane occupancy —
            # the same groups /metrics exports as ray_tpu_node_submit
            # / ray_tpu_node_dispatch.
            "pipeline": {"submit": pipeline.get("submit", {}),
                         "dispatch": pipeline.get("dispatch", {})}}


# ------------------------------------------------------------------ actors


def list_actors(filters=None, limit: int = 100) -> list[dict]:
    """Reference: `ray list actors` (api.py:782)."""
    rows = [
        {
            "actor_id": rec.actor_id.hex(),
            "class_name": rec.class_name,
            "state": rec.state,
            "name": rec.name,
            "namespace": rec.namespace,
            "num_restarts": rec.num_restarts,
            "death_cause": rec.death_cause,
            "node_id": rec.node_id_hex,
            "pid": rec.pid,
        }
        for rec in _runtime().gcs.list_actors()
    ]
    return _apply_filters(rows, filters, limit)


def get_actor(actor_id: str) -> dict | None:
    for row in list_actors(limit=10**9):
        if row["actor_id"] == actor_id:
            return row
    return None


def summarize_actors() -> dict:
    summary: dict[str, dict[str, int]] = {}
    for row in list_actors(limit=10**9):
        per_class = summary.setdefault(row["class_name"], {})
        per_class[row["state"]] = per_class.get(row["state"], 0) + 1
    return {"summary": summary}


# ----------------------------------------------------------------- objects


def list_objects(filters=None, limit: int = 100) -> list[dict]:
    """Reference: `ray list objects` (api.py:1060)."""
    from ray_tpu._private.ids import ObjectID

    runtime = _runtime()
    with runtime._locations_lock:
        locations = {oid.hex(): nid.hex()
                     for oid, nid in runtime._object_locations.items()}
    rows = []
    for entry in runtime.store.snapshot():
        rows.append({
            "object_id": entry["object_id"],
            "state": entry["state"],
            "size_bytes": entry["size_bytes"],
            "reference_count": runtime.reference_counter.count(
                ObjectID.from_hex(entry["object_id"])),
            "spilled": entry["spilled"],
            "node_id": locations.get(entry["object_id"], ""),
        })
    return _apply_filters(rows, filters, limit)


def summarize_objects() -> dict:
    total = 0
    bytes_total = 0
    by_state: dict[str, int] = {}
    for row in list_objects(limit=10**9):
        total += 1
        bytes_total += row["size_bytes"]
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return {"total_objects": total, "total_size_bytes": bytes_total,
            "by_state": by_state}


# ------------------------------------------------------------------- nodes


def list_nodes(filters=None, limit: int = 100) -> list[dict]:
    rows = [
        {
            "node_id": rec.node_id.hex(),
            "state": "ALIVE" if rec.alive else "DEAD",
            "address": rec.address,
            "resources": dict(rec.resources),
            "labels": dict(rec.labels),
        }
        for rec in _runtime().gcs.list_nodes()
    ]
    return _apply_filters(rows, filters, limit)


def get_node(node_id: str) -> dict | None:
    for row in list_nodes(limit=10**9):
        if row["node_id"] == node_id:
            return row
    return None


# --------------------------------------------------------- placement groups


def list_placement_groups(filters=None, limit: int = 100) -> list[dict]:
    rows = [
        {
            "placement_group_id": rec["pg_id"],
            "state": rec["state"],
            "strategy": rec["strategy"],
            "bundles": rec["bundles"],
        }
        for rec in _runtime().placement_groups.snapshot()
    ]
    return _apply_filters(rows, filters, limit)


# -------------------------------------------------------------------- jobs


def list_jobs(filters=None, limit: int = 100) -> list[dict]:
    rows = [
        {
            "job_id": rec.job_id.hex(),
            "status": rec.status,
            "start_time": rec.start_time,
            "end_time": rec.end_time,
        }
        for rec in _runtime().gcs.list_jobs()
    ]
    return _apply_filters(rows, filters, limit)


# --------------------------------------------------------------------- CLI


def _cli(argv: list[str]) -> int:
    import json

    listings = {
        "tasks": list_tasks, "actors": list_actors, "objects": list_objects,
        "nodes": list_nodes, "placement-groups": list_placement_groups,
        "jobs": list_jobs,
    }
    summaries = {"tasks": summarize_tasks, "actors": summarize_actors,
                 "objects": summarize_objects,
                 "placement": summarize_placement}
    if argv and argv[0] == "timeline":
        return _cli_timeline(argv[1:])
    if argv and argv[0] == "debug":
        return _cli_debug(argv[1:])
    if argv and argv[0] == "top":
        return _cli_top(argv[1:])
    if argv and argv[0] == "doctor":
        return _cli_doctor(argv[1:])
    if argv and argv[0] == "summary" and len(argv) == 1:
        # `python -m ray_tpu summary` — the per-function latency/
        # resource summary is the flagship view; default to tasks.
        argv = ["summary", "tasks"]
    if len(argv) < 2:
        print("usage: python -m ray_tpu.util.state "
              "{list|summary} <resource> | summary | "
              "summary placement | "
              "timeline [output.json] | debug [bundle.json]")
        return 2
    verb, resource = argv[0], argv[1]
    table = listings if verb == "list" else summaries if verb == "summary" else None
    if table is None or resource not in table:
        print(f"unknown: {verb} {resource}; resources: {sorted(table or listings)}")
        return 2
    _ensure_connected()
    print(json.dumps(table[resource](), indent=2, default=str))
    return 0


def _ensure_connected() -> None:
    """CLI entry: attach to a running cluster when one is reachable,
    else a local runtime (mirrors the timeline CLI's behavior)."""
    import ray_tpu

    if worker_mod.global_runtime() is not None:
        return
    try:
        ray_tpu.init(address="auto", num_cpus=0,
                     ignore_reinit_error=True)
    except (ConnectionError, OSError):
        ray_tpu.init(ignore_reinit_error=True)


def collect_debug_bundle(out_path: str) -> dict:
    """``ray_tpu debug``: one post-mortem bundle from everything
    reachable — the session dir's dumped flight-recorder rings (dead
    daemons included), every live node's ring + fault/breaker/stage
    state over the ``flight_ring`` RPC, this driver's own ring, and
    the GCS node-stats aggregation table (reference intent: `ray
    cluster-dump`). Works degraded: with no cluster reachable the
    bundle still carries the session-dir dumps."""
    import json
    import time

    from ray_tpu._private import flight_recorder, perf_plane
    from ray_tpu._private.rpc import RpcClient, breaker_stats

    bundle: dict = {
        "collected_at": time.time(),
        "session_dir": flight_recorder.flight_dir(),
        "session_dumps": flight_recorder.collect_session_dumps(),
        "nodes": {},
    }
    runtime = worker_mod.global_runtime()
    if runtime is not None:
        rec = flight_recorder.get()
        bundle["driver"] = {
            **(rec.snapshot() if rec is not None else {"events": []}),
            "fault_stats": runtime.fault_stats(),
            "breaker": breaker_stats(),
            "stage_hist": perf_plane.stage_snapshot(),
        }
        # Cluster history plane: the head's windowed per-node history
        # and the watchdog's verdicts — what happened in the last two
        # minutes, not just cumulative-since-boot state. None for
        # local-only runtimes / pre-plane heads.
        bundle["metrics_history"] = runtime.metrics_history(
            window_s=120.0)
        bundle["cluster_health"] = runtime.cluster_health()
        client = getattr(runtime, "gcs_client", None)
        if client is not None:
            try:
                bundle["gcs_node_stats"] = client.call(
                    "node_stats", timeout_s=3.0)
            except Exception:  # noqa: BLE001 — head unreachable
                bundle["gcs_node_stats"] = {}
            try:
                node_rows = client.call("list_nodes")
            except Exception:  # noqa: BLE001
                node_rows = []
        else:
            bundle["gcs_node_stats"] = runtime.gcs.node_stats()
            node_rows = [{"node_id": r.node_id.hex(),
                          "alive": r.alive,
                          "executor_address": r.executor_address}
                         for r in runtime.gcs.list_nodes()]
        for row in node_rows:
            addr = row.get("executor_address")
            if not row.get("alive") or not addr:
                continue
            try:
                client = RpcClient(addr, timeout_s=3.0,
                                   connect_timeout_s=2.0)
                try:
                    ring = client.call("flight_ring")
                finally:
                    client.close()
            except Exception as exc:  # noqa: BLE001 — skip unreachable
                ring = {"error": f"unreachable: {type(exc).__name__}"}
            bundle["nodes"][row.get("node_id", addr)[:16]] = ring
    with open(out_path, "w") as f:
        json.dump(bundle, f, indent=2, default=str)
    return bundle


def _cli_debug(argv: list[str]) -> int:
    out = argv[0] if argv else "ray_tpu_debug_bundle.json"
    try:
        _ensure_connected()
    except Exception as exc:  # noqa: BLE001 — degraded bundle still useful
        print(f"note: no cluster reachable ({exc}); collecting "
              f"session-dir dumps only")
    bundle = collect_debug_bundle(out)
    rings = len(bundle.get("session_dumps", [])) \
        + len(bundle.get("nodes", {})) \
        + (1 if "driver" in bundle else 0)
    print(f"wrote {out}: {rings} flight-recorder rings "
          f"({len(bundle.get('session_dumps', []))} dumped files, "
          f"{len(bundle.get('nodes', {}))} live nodes)")
    return 0


def _cli_timeline(argv: list[str]) -> int:
    """``ray_tpu timeline [output.json]`` — export the merged chrome
    trace (reference: `ray timeline`). Connects to a running cluster
    when one is reachable (pulling the daemons' heartbeat-shipped
    spans); otherwise exports the local runtime's view. Task events
    live per driver, so a driver exporting from inside its own script
    (``tracing.export_chrome_trace``) sees strictly more."""
    out = argv[0] if argv else "ray_tpu_timeline.json"
    import ray_tpu
    from ray_tpu.util import tracing

    if worker_mod.global_runtime() is None:
        try:
            ray_tpu.init(address="auto", num_cpus=0,
                         ignore_reinit_error=True)
        except (ConnectionError, OSError):
            ray_tpu.init(ignore_reinit_error=True)
    n = tracing.export_chrome_trace(out)
    print(f"wrote {n} events to {out} "
          f"(open in chrome://tracing or https://ui.perfetto.dev)")
    return 0


# ------------------------------------------------------ history plane CLI

_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(values: list, width: int = 24) -> str:
    """One unicode block per interval sample, scaled to the window's
    peak (an all-zero window renders as a flat floor, not blanks)."""
    vals = [max(0.0, float(v or 0.0)) for v in values][-width:]
    if not vals:
        return ""
    peak = max(vals)
    if peak <= 0.0:
        return _SPARK_CHARS[0] * len(vals)
    top = len(_SPARK_CHARS) - 1
    return "".join(
        _SPARK_CHARS[min(top, int(v / peak * top + 0.5))] for v in vals)


def _fetch_history_health(window_s: float):
    runtime = _runtime()
    return (runtime.metrics_history(window_s=window_s),
            runtime.cluster_health())


def _render_top(hist: dict | None, health: dict | None) -> list[str]:
    """The `top` frame: per-node windowed rates + task-rate sparkline
    + active verdicts, rendered from one metrics_history/cluster_health
    query pair."""
    lines: list[str] = []
    if not hist or not hist.get("armed"):
        lines.append(
            "history plane unavailable (no head reachable, a pre-plane "
            "head, or metrics_history=0)")
        return lines
    nodes = hist.get("nodes") or {}
    degraded = hist.get("degraded") or []
    lines.append(
        f"cluster history — {len(nodes)} node(s), "
        f"interval {hist.get('interval_s', 0):g}s, "
        f"window {hist.get('window_s', 0):g}s"
        + (f", DEGRADED shard domains {degraded}" if degraded else ""))
    lines.append(
        f"{'NODE':<18}{'TASKS/S':>9}{'SHED/S':>8}{'RETRY/S':>9}"
        f"{'SPILL/S':>9}{'RUN':>5}{'DEPTH':>7}  HISTORY(tasks/s)")
    for node_hex, row in sorted(nodes.items()):
        rates = row.get("rates") or {}
        samples = row.get("samples") or []
        latest = samples[-1] if samples else {}
        spark = _sparkline(
            [s.get("tasks_executed", 0.0) for s in samples])
        mark = "*" if row.get("stale") else " "
        lines.append(
            f"{node_hex[:16]:<17}{mark}"
            f"{rates.get('tasks_executed', 0.0):>9.2f}"
            f"{rates.get('admission_shed', 0.0):>8.2f}"
            f"{rates.get('rpc_retries', 0.0):>9.2f}"
            f"{rates.get('spills', 0.0):>9.2f}"
            f"{int(latest.get('running', 0) or 0):>5}"
            f"{int(latest.get('depth', 0) or 0):>7}  {spark}")
    if degraded:
        lines.append("  * = stale samples (shard domain stalled)")
    verdicts = (health or {}).get("verdicts") or []
    if verdicts:
        lines.append(f"active verdicts ({len(verdicts)}):")
        for verdict in verdicts:
            lines.append(
                f"  [{verdict.get('rule')}] {verdict.get('node')}: "
                f"{verdict.get('detail')}")
    else:
        lines.append("active verdicts: none")
    return lines


def _cli_top(argv: list[str]) -> int:
    """``ray_tpu top`` — live per-node rate view over the head's
    history plane, refreshing every --interval seconds (ctrl-c to
    stop; --iterations N for a bounded run)."""
    import argparse
    import time as _time

    parser = argparse.ArgumentParser(prog="ray_tpu top")
    parser.add_argument("--window", type=float, default=60.0,
                        help="rate window in seconds")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period")
    parser.add_argument("--iterations", type=int, default=0,
                        help="frames to render (0 = until ctrl-c)")
    parser.add_argument("--no-clear", action="store_true",
                        help="append frames instead of redrawing")
    args = parser.parse_args(argv)
    _ensure_connected()
    rendered = 0
    try:
        while True:
            hist, health = _fetch_history_health(args.window)
            frame = _render_top(hist, health)
            if not args.no_clear and rendered:
                print("\033[2J\033[H", end="")
            print("\n".join(frame))
            rendered += 1
            if args.iterations and rendered >= args.iterations:
                return 0
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _cli_doctor(argv: list[str]) -> int:
    """``ray_tpu doctor`` — one-shot health report: every active
    verdict with the evidence window behind it, the recently-fired
    ring, and any degraded shard domains. Exit 1 when verdicts are
    active (scriptable health check), 0 on a clean cluster."""
    import argparse
    import json as _json

    parser = argparse.ArgumentParser(prog="ray_tpu doctor")
    parser.add_argument("--window", type=float, default=120.0,
                        help="history window behind the report")
    parser.add_argument("--json", action="store_true",
                        help="emit the raw report as JSON")
    args = parser.parse_args(argv)
    _ensure_connected()
    hist, health = _fetch_history_health(args.window)
    if not health or not health.get("armed"):
        print("health watchdog unavailable (no head reachable, a "
              "pre-plane head, or metrics_history=0)")
        return 2
    if args.json:
        print(_json.dumps({"cluster_health": health,
                           "metrics_history": hist},
                          indent=2, default=str))
        return 1 if health.get("verdicts") else 0
    verdicts = health.get("verdicts") or []
    fired_total = health.get("fired_total") or {}
    nodes = (hist or {}).get("nodes") or {}
    degraded = (health.get("degraded")
                or (hist or {}).get("degraded") or [])
    print(f"ray_tpu doctor — {len(verdicts)} active verdict(s), "
          f"{sum(fired_total.values())} fired since head start")
    for verdict in verdicts:
        print(f"[{verdict.get('rule')}] {verdict.get('node')}: "
              f"{verdict.get('detail')}  "
              f"(value={verdict.get('value')}, "
              f"threshold={verdict.get('threshold')}, "
              f"window={verdict.get('window_s')}s)")
        evidence = verdict.get("evidence")
        if evidence:
            print(f"    evidence: "
                  f"{_json.dumps(evidence, default=str, sort_keys=True)}")
    if degraded:
        print(f"degraded shard domains: {degraded} — history for "
              f"their nodes is stale-marked")
    if fired_total:
        per_rule = ", ".join(f"{rule}={n}" for rule, n
                             in sorted(fired_total.items()))
        print(f"fired totals by rule: {per_rule}")
    print(f"history: {len(nodes)} node(s) over "
          f"{(hist or {}).get('window_s', 0):g}s "
          f"(interval {(hist or {}).get('interval_s', 0):g}s)")
    if not verdicts:
        print("no active verdicts — cluster healthy")
    return 1 if verdicts else 0
