"""State API implementation (reference: python/ray/util/state/api.py).

Every listing returns plain dicts, newest-first where a time exists,
with reference-style filters: ``filters=[("state", "=", "FAILED")]``
supports ``=``/``!=``, and ``limit`` caps the result size.
"""

from __future__ import annotations

from typing import Any

from ray_tpu._private import worker as worker_mod


def _runtime():
    runtime = worker_mod.global_runtime()
    if runtime is None:
        raise RuntimeError("ray_tpu is not initialized")
    return runtime


def _apply_filters(rows: list[dict], filters, limit: int) -> list[dict]:
    for key, op, value in (filters or []):
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"Unsupported filter op {op!r}; use '=' or '!='")
    return rows[:limit]


# ------------------------------------------------------------------- tasks


def list_tasks(filters=None, limit: int = 100) -> list[dict]:
    """Reference: `ray list tasks` (api.py:1014)."""
    events = _runtime().gcs.list_task_events()
    rows = [
        {
            "task_id": ev.task_id.hex(),
            "name": ev.name,
            "state": ev.state,
            "node_id": ev.node_id,
            "start_time": ev.start_time,
            "end_time": ev.end_time,
            "error": ev.error,
        }
        for ev in events
    ]
    rows.sort(key=lambda r: r["start_time"], reverse=True)
    return _apply_filters(rows, filters, limit)


def get_task(task_id: str) -> dict | None:
    for row in list_tasks(limit=10**9):
        if row["task_id"] == task_id:
            return row
    return None


def summarize_tasks() -> dict:
    """Counts by (name, state) (reference: summarize_tasks api.py:1376)."""
    summary: dict[str, dict[str, int]] = {}
    for row in list_tasks(limit=10**9):
        per_name = summary.setdefault(row["name"], {})
        per_name[row["state"]] = per_name.get(row["state"], 0) + 1
    return {"node_count": len(list_nodes(limit=10**9)), "summary": summary}


# ------------------------------------------------------------------ actors


def list_actors(filters=None, limit: int = 100) -> list[dict]:
    """Reference: `ray list actors` (api.py:782)."""
    rows = [
        {
            "actor_id": rec.actor_id.hex(),
            "class_name": rec.class_name,
            "state": rec.state,
            "name": rec.name,
            "namespace": rec.namespace,
            "num_restarts": rec.num_restarts,
            "death_cause": rec.death_cause,
            "node_id": rec.node_id_hex,
            "pid": rec.pid,
        }
        for rec in _runtime().gcs.list_actors()
    ]
    return _apply_filters(rows, filters, limit)


def get_actor(actor_id: str) -> dict | None:
    for row in list_actors(limit=10**9):
        if row["actor_id"] == actor_id:
            return row
    return None


def summarize_actors() -> dict:
    summary: dict[str, dict[str, int]] = {}
    for row in list_actors(limit=10**9):
        per_class = summary.setdefault(row["class_name"], {})
        per_class[row["state"]] = per_class.get(row["state"], 0) + 1
    return {"summary": summary}


# ----------------------------------------------------------------- objects


def list_objects(filters=None, limit: int = 100) -> list[dict]:
    """Reference: `ray list objects` (api.py:1060)."""
    from ray_tpu._private.ids import ObjectID

    runtime = _runtime()
    with runtime._locations_lock:
        locations = {oid.hex(): nid.hex()
                     for oid, nid in runtime._object_locations.items()}
    rows = []
    for entry in runtime.store.snapshot():
        rows.append({
            "object_id": entry["object_id"],
            "state": entry["state"],
            "size_bytes": entry["size_bytes"],
            "reference_count": runtime.reference_counter.count(
                ObjectID.from_hex(entry["object_id"])),
            "spilled": entry["spilled"],
            "node_id": locations.get(entry["object_id"], ""),
        })
    return _apply_filters(rows, filters, limit)


def summarize_objects() -> dict:
    total = 0
    bytes_total = 0
    by_state: dict[str, int] = {}
    for row in list_objects(limit=10**9):
        total += 1
        bytes_total += row["size_bytes"]
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return {"total_objects": total, "total_size_bytes": bytes_total,
            "by_state": by_state}


# ------------------------------------------------------------------- nodes


def list_nodes(filters=None, limit: int = 100) -> list[dict]:
    rows = [
        {
            "node_id": rec.node_id.hex(),
            "state": "ALIVE" if rec.alive else "DEAD",
            "address": rec.address,
            "resources": dict(rec.resources),
            "labels": dict(rec.labels),
        }
        for rec in _runtime().gcs.list_nodes()
    ]
    return _apply_filters(rows, filters, limit)


def get_node(node_id: str) -> dict | None:
    for row in list_nodes(limit=10**9):
        if row["node_id"] == node_id:
            return row
    return None


# --------------------------------------------------------- placement groups


def list_placement_groups(filters=None, limit: int = 100) -> list[dict]:
    rows = [
        {
            "placement_group_id": rec["pg_id"],
            "state": rec["state"],
            "strategy": rec["strategy"],
            "bundles": rec["bundles"],
        }
        for rec in _runtime().placement_groups.snapshot()
    ]
    return _apply_filters(rows, filters, limit)


# -------------------------------------------------------------------- jobs


def list_jobs(filters=None, limit: int = 100) -> list[dict]:
    rows = [
        {
            "job_id": rec.job_id.hex(),
            "status": rec.status,
            "start_time": rec.start_time,
            "end_time": rec.end_time,
        }
        for rec in _runtime().gcs.list_jobs()
    ]
    return _apply_filters(rows, filters, limit)


# --------------------------------------------------------------------- CLI


def _cli(argv: list[str]) -> int:
    import json

    listings = {
        "tasks": list_tasks, "actors": list_actors, "objects": list_objects,
        "nodes": list_nodes, "placement-groups": list_placement_groups,
        "jobs": list_jobs,
    }
    summaries = {"tasks": summarize_tasks, "actors": summarize_actors,
                 "objects": summarize_objects}
    if argv and argv[0] == "timeline":
        return _cli_timeline(argv[1:])
    if len(argv) < 2:
        print("usage: python -m ray_tpu.util.state "
              "{list|summary} <resource> | timeline [output.json]")
        return 2
    verb, resource = argv[0], argv[1]
    table = listings if verb == "list" else summaries if verb == "summary" else None
    if table is None or resource not in table:
        print(f"unknown: {verb} {resource}; resources: {sorted(table or listings)}")
        return 2
    print(json.dumps(table[resource](), indent=2, default=str))
    return 0


def _cli_timeline(argv: list[str]) -> int:
    """``ray_tpu timeline [output.json]`` — export the merged chrome
    trace (reference: `ray timeline`). Connects to a running cluster
    when one is reachable (pulling the daemons' heartbeat-shipped
    spans); otherwise exports the local runtime's view. Task events
    live per driver, so a driver exporting from inside its own script
    (``tracing.export_chrome_trace``) sees strictly more."""
    out = argv[0] if argv else "ray_tpu_timeline.json"
    import ray_tpu
    from ray_tpu.util import tracing

    if worker_mod.global_runtime() is None:
        try:
            ray_tpu.init(address="auto", num_cpus=0,
                         ignore_reinit_error=True)
        except (ConnectionError, OSError):
            ray_tpu.init(ignore_reinit_error=True)
    n = tracing.export_chrome_trace(out)
    print(f"wrote {n} events to {out} "
          f"(open in chrome://tracing or https://ui.perfetto.dev)")
    return 0
