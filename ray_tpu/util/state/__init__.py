"""State API: list/get/summarize cluster state.

Reference: python/ray/util/state/api.py (list_tasks :1014, list_actors
:782, list_objects :1060, list_nodes :876, list_placement_groups :831,
list_jobs :922, summarize_* :1376-1444). Backed directly by the GCS
tables, the object store, and the placement-group ledger.

Also runnable as a CLI, mirroring `ray list ...`:
    python -m ray_tpu.util.state list tasks
    python -m ray_tpu.util.state summary tasks
"""

from ray_tpu.util.state.api import (  # noqa: F401
    get_actor,
    get_node,
    get_task,
    list_actors,
    list_jobs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    summarize_actors,
    summarize_objects,
    summarize_tasks,
)
