"""User-facing scheduling strategy dataclasses.

Reference: python/ray/util/scheduling_strategies.py:15/41/135.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


@dataclass
class NodeLabelSchedulingStrategy:
    hard: dict | None = None
    soft: dict | None = None
