"""HuggingFace Transformers integration for Train.

Reference: python/ray/train/huggingface/ (TransformersTrainer wraps a
🤗 training loop in Ray Train's worker-group orchestration). TPU-first
shape: the model is a FLAX transformer whose params train under a
jitted optax step inside JaxTrainer's worker loop — no torch, no
Trainer-callback shimming; the integration is a prepared train loop
plus helpers, and the orchestration (gangs, checkpoints, failure
configs) is plain JaxTrainer.

Usage::

    from transformers import FlaxGPT2LMHeadModel, GPT2Config

    def make_model():
        return FlaxGPT2LMHeadModel(GPT2Config(...))

    trainer = TransformersTrainer(
        make_model,
        train_dataset=token_batches,     # iterable of {"input_ids": [B, T]}
        optimizer=optax.adamw(3e-4),
        num_epochs=2,
        scaling_config=ScalingConfig(num_workers=1),
    )
    result = trainer.fit()
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

from ray_tpu.train.trainer import JaxTrainer


def causal_lm_loss_fn(model) -> Callable:
    """Standard next-token cross-entropy for Flax causal-LM heads
    (reference: transformers' CLM objective). Runs the model in TRAIN
    mode with a per-step dropout rng — configured dropout must apply
    during training."""
    import jax.numpy as jnp
    import optax as _optax

    def loss_fn(params, batch, dropout_rng):
        input_ids = batch["input_ids"]
        outputs = model(input_ids=input_ids, params=params,
                        dropout_rng=dropout_rng, train=True)
        logits = outputs.logits[:, :-1]
        targets = input_ids[:, 1:]
        mask = batch.get("attention_mask")
        token_losses = _optax.softmax_cross_entropy_with_integer_labels(
            logits, targets)
        if mask is not None:
            mask = mask[:, 1:].astype(token_losses.dtype)
            return (token_losses * mask).sum() / jnp.maximum(
                mask.sum(), 1.0)
        return token_losses.mean()

    return loss_fn


def make_transformers_train_loop(
        model_factory: Callable[[], Any],
        train_dataset: Iterable,
        optimizer=None,
        loss_fn_factory: Callable = causal_lm_loss_fn,
        num_epochs: int = 1,
        report_every: int = 10) -> Callable:
    """Build a JaxTrainer-compatible ``train_loop_per_worker``: one
    jitted (loss, grad, optax update) program per worker, batches from
    ``train_dataset`` (an iterable of numpy dicts or a
    ray_tpu.data.Dataset), loss reported through the session.

    ``loss_fn_factory(model)`` must return
    ``loss_fn(params, batch, dropout_rng) -> scalar`` (the rng keeps
    configured dropout active in training mode)."""

    def train_loop(config: dict | None = None):
        import jax
        import numpy as np
        import optax as _optax

        from ray_tpu.train import session

        model = model_factory()
        opt = optimizer if optimizer is not None else _optax.adamw(3e-4)
        loss_fn = loss_fn_factory(model)
        params = model.params
        opt_state = opt.init(params)
        rng = jax.random.PRNGKey(
            int((config or {}).get("seed", 0)))

        @jax.jit
        def step(params, opt_state, batch, rng):
            rng, dropout_rng = jax.random.split(rng)
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, dropout_rng)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (_optax.apply_updates(params, updates), opt_state,
                    loss, rng)

        def batches():
            ds = train_dataset
            if hasattr(ds, "iter_batches"):  # ray_tpu.data.Dataset
                yield from ds.iter_batches(batch_format="numpy")
            else:
                yield from ds

        step_idx = 0
        last_loss = None
        for _ in range(num_epochs):
            for batch in batches():
                batch = {k: np.asarray(v) for k, v in batch.items()}
                params, opt_state, loss, rng = step(
                    params, opt_state, batch, rng)
                step_idx += 1
                last_loss = float(loss)
                if step_idx % report_every == 0:
                    session.report({"loss": last_loss,
                                    "step": step_idx})
        session.report({"loss": last_loss, "step": step_idx,
                        "done": True})

    return train_loop


class TransformersTrainer(JaxTrainer):
    """JaxTrainer pre-wired for Flax 🤗 models (reference:
    train/huggingface/transformers_trainer.py — same role, TPU-native
    internals: the loop is a jitted optax step, not a wrapped
    torch Trainer)."""

    def __init__(self, model_factory: Callable[[], Any],
                 *, train_dataset: Iterable,
                 optimizer=None,
                 loss_fn_factory: Callable = causal_lm_loss_fn,
                 num_epochs: int = 1,
                 report_every: int = 10,
                 **kwargs):
        super().__init__(
            make_transformers_train_loop(
                model_factory, train_dataset, optimizer,
                loss_fn_factory, num_epochs, report_every),
            **kwargs)
