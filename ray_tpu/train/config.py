"""Shared training config dataclasses.

Reference: python/ray/air/config.py — ScalingConfig (:101), FailureConfig
(:377), CheckpointConfig (:427), RunConfig (:576). TPU-native twist:
ScalingConfig speaks chips/hosts and placement is slice-aware
(STRICT_PACK over a pod slice), since a TPU slice fails and is acquired
as a unit (SURVEY §7 hard parts).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class ScalingConfig:
    """How many workers and what each worker holds.

    num_workers: actor count in the worker group (1 per TPU host in a
    real slice; threads in the single-node slice).
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "PACK"
    # TPU topology hints.
    chips_per_worker: int = 0
    # Each gang member gets a dedicated OS process (one JAX process per
    # worker — required for a jax.distributed multi-process SPMD mesh;
    # thread workers share one JAX runtime and cannot form one).
    use_process_workers: bool = False
    # Extra env for process workers (e.g. XLA_FLAGS for virtual-device
    # meshes in tests), applied before the worker's first JAX use.
    worker_env: dict[str, str] = field(default_factory=dict)

    def worker_resources(self) -> dict[str, float]:
        res = dict(self.resources_per_worker)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = float(self.chips_per_worker or 1)
        if "CPU" not in res:
            res["CPU"] = 1.0
        return res


@dataclass
class FailureConfig:
    """Reference: air/config.py:377. max_failures: group-level restarts;
    a TPU slice fails as a unit, so recovery re-forms the whole group."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Reference: air/config.py:427."""

    num_to_keep: int | None = None
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False


@dataclass
class RunConfig:
    """Reference: air/config.py:576."""

    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: dict[str, Any] | None = None
    verbose: int = 0
    # Max seconds between worker reports before the run is declared hung.
    # Large default: the first report waits on the full XLA compile of the
    # sharded train step, which for 7B-class models takes many minutes.
    report_timeout_s: float = 3600.0
