"""WorkerGroup — the gang of training actors.

Reference: python/ray/train/_internal/worker_group.py:102 (WorkerGroup of
actors placed per ScalingConfig) and backend_executor.py:65/:124/:438
(start, start_training). Placement uses a placement group so the gang is
scheduled all-or-nothing (slice semantics for TPU).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, _SessionState
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class _TrainChannel:
    """Driver-side report/stop channel for PROCESS worker gangs: queue
    objects cannot cross the process boundary, so workers report through
    this actor (via the nested-submission path) and learn the stop flag
    from each report's reply (reference: session reports stream back to
    the driver and carry stop decisions)."""

    def __init__(self):
        self._msgs: list = []
        self._stop = False

    def report(self, msg: dict) -> bool:
        self._msgs.append(msg)
        return self._stop

    def drain(self) -> list:
        out, self._msgs = self._msgs, []
        return out

    def set_stop(self) -> None:
        self._stop = True


class _ChannelReporter:
    """Worker-side queue/stop shim over the channel actor."""

    class _Flag:
        def __init__(self):
            self._set = False

        def is_set(self) -> bool:
            return self._set

    def __init__(self, channel_handle):
        self._channel = channel_handle
        self.stop_flag = self._Flag()

    def put(self, msg: dict) -> None:
        import ray_tpu

        if ray_tpu.get(self._channel.report.remote(msg)):
            self.stop_flag._set = True


@ray_tpu.remote
class TrainWorker:
    """One member of the gang; runs the user loop in its actor thread
    (or its own process when the gang is a multi-process SPMD world)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size

    def run(self, fn: Callable, config: dict, results_queue,
            stop_event, resume_checkpoint) -> Any:
        from ray_tpu.train.session import run_with_session

        if stop_event is None:
            # Process-worker gang: results_queue is a channel actor
            # handle; replies double as the stop signal.
            reporter = _ChannelReporter(results_queue)
            results_queue = reporter
            stop_event = reporter.stop_flag
        state = _SessionState(
            context=TrainContext(world_size=self.world_size,
                                 world_rank=self.rank,
                                 local_rank=self.rank),
            results_queue=results_queue,
            resume_checkpoint=resume_checkpoint,
            stop_event=stop_event,
        )

        def emit(msg: dict):
            results_queue.put({"rank": self.rank, **msg})

        try:
            return run_with_session(fn, config, state, emit)
        except BaseException:  # noqa: BLE001 — already emitted; fail the ref
            raise

    def ping(self) -> str:
        return "ok"


class WorkerGroup:
    """Creates, supervises and tears down the gang."""

    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling
        self.workers: list = []
        self.pg = None
        self.channel = None
        self._pump_stop = threading.Event()
        self._start()

    def _start(self):
        n = self.scaling.num_workers
        resources = self.scaling.worker_resources()
        bundles = [dict(resources) for _ in range(n)]
        self.pg = placement_group(bundles, strategy=self.scaling.placement_strategy)
        if not self.pg.wait(timeout_seconds=60):
            remove_placement_group(self.pg)
            raise TimeoutError(
                f"Could not reserve {n} x {resources} for the worker group")
        strategy = PlacementGroupSchedulingStrategy(placement_group=self.pg)
        worker_cls = TrainWorker.options(
            resources={k: v for k, v in resources.items()},
            num_cpus=0,
            scheduling_strategy=strategy,
        )
        if self.scaling.use_process_workers:
            options: dict = {"process": True}
            if self.scaling.worker_env:
                options["runtime_env"] = {
                    "env_vars": dict(self.scaling.worker_env)}
            worker_cls = worker_cls.options(**options)
            self.channel = _TrainChannel.remote()
        try:
            self.workers = [worker_cls.remote(rank, n) for rank in range(n)]
            ray_tpu.get([w.ping.remote() for w in self.workers], timeout=60)
        except BaseException:
            # Don't leak the committed bundles or half-started gang.
            self.shutdown()
            raise

    def run(self, fn: Callable, config: dict, results_queue,
            stop_event, resume_checkpoint) -> list:
        """Kick off the loop on every worker; returns refs."""
        if self.channel is not None:
            # Process gang: workers report through the channel actor; a
            # driver-side pump forwards into the local results queue and
            # relays the local stop event to the channel.
            self._start_pump(results_queue, stop_event)
            return [
                w.run.remote(fn, config, self.channel, None,
                             resume_checkpoint)
                for w in self.workers
            ]
        return [
            w.run.remote(fn, config, results_queue, stop_event, resume_checkpoint)
            for w in self.workers
        ]

    def _start_pump(self, results_queue, stop_event) -> None:
        def pump():
            stop_sent = False
            while not self._pump_stop.is_set():
                try:
                    for msg in ray_tpu.get(self.channel.drain.remote()):
                        results_queue.put(msg)
                except Exception:  # noqa: BLE001 — channel dying = done
                    return
                if stop_event.is_set() and not stop_sent:
                    stop_sent = True
                    try:
                        self.channel.set_stop.remote()
                    except Exception:  # noqa: BLE001
                        pass
                self._pump_stop.wait(0.05)

        threading.Thread(target=pump, daemon=True,
                         name="train-channel-pump").start()

    def shutdown(self):
        self._pump_stop.set()
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass  # worker already dead at teardown
        if self.channel is not None:
            try:
                ray_tpu.kill(self.channel)
            except Exception:
                pass  # channel already dead at teardown
            self.channel = None
        if self.pg is not None:
            remove_placement_group(self.pg)
        self.workers = []
