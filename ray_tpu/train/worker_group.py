"""WorkerGroup — the gang of training actors.

Reference: python/ray/train/_internal/worker_group.py:102 (WorkerGroup of
actors placed per ScalingConfig) and backend_executor.py:65/:124/:438
(start, start_training). Placement uses a placement group so the gang is
scheduled all-or-nothing (slice semantics for TPU).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, _SessionState
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class TrainWorker:
    """One member of the gang; runs the user loop in its actor thread."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size

    def run(self, fn: Callable, config: dict, results_queue,
            stop_event, resume_checkpoint) -> Any:
        from ray_tpu.train.session import run_with_session

        state = _SessionState(
            context=TrainContext(world_size=self.world_size,
                                 world_rank=self.rank,
                                 local_rank=self.rank),
            results_queue=results_queue,
            resume_checkpoint=resume_checkpoint,
            stop_event=stop_event,
        )

        def emit(msg: dict):
            results_queue.put({"rank": self.rank, **msg})

        try:
            return run_with_session(fn, config, state, emit)
        except BaseException:  # noqa: BLE001 — already emitted; fail the ref
            raise

    def ping(self) -> str:
        return "ok"


class WorkerGroup:
    """Creates, supervises and tears down the gang."""

    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling
        self.workers: list = []
        self.pg = None
        self._start()

    def _start(self):
        n = self.scaling.num_workers
        resources = self.scaling.worker_resources()
        bundles = [dict(resources) for _ in range(n)]
        self.pg = placement_group(bundles, strategy=self.scaling.placement_strategy)
        if not self.pg.wait(timeout_seconds=60):
            remove_placement_group(self.pg)
            raise TimeoutError(
                f"Could not reserve {n} x {resources} for the worker group")
        strategy = PlacementGroupSchedulingStrategy(placement_group=self.pg)
        try:
            self.workers = [
                TrainWorker.options(
                    resources={k: v for k, v in resources.items()},
                    num_cpus=0,
                    scheduling_strategy=strategy,
                ).remote(rank, n)
                for rank in range(n)
            ]
            ray_tpu.get([w.ping.remote() for w in self.workers], timeout=60)
        except BaseException:
            # Don't leak the committed bundles or half-started gang.
            self.shutdown()
            raise

    def run(self, fn: Callable, config: dict, results_queue,
            stop_event, resume_checkpoint) -> list:
        """Kick off the loop on every worker; returns refs."""
        return [
            w.run.remote(fn, config, results_queue, stop_event, resume_checkpoint)
            for w in self.workers
        ]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            remove_placement_group(self.pg)
        self.workers = []
