"""Trainers: BaseTrainer / DataParallelTrainer / JaxTrainer.

Reference: python/ray/train/base_trainer.py:561 (fit),
data_parallel_trainer.py:22/:419 (worker-group orchestration),
torch/torch_trainer.py:11 (framework trainer). The TPU-native framework
trainer is ``JaxTrainer``: the worker group is the SPMD unit and the
in-loop API hands each worker a mesh + sharded step instead of wrapping
a model in DDP.

Failure semantics follow the slice model (SURVEY §7 hard parts): on a
worker failure with FailureConfig(max_failures=N), the *whole group* is
torn down, re-formed, and restarted from the latest reported checkpoint.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger("ray_tpu.train")


@dataclass
class Result:
    """Reference: ray.air.Result."""

    metrics: dict = field(default_factory=dict)
    checkpoint: Checkpoint | None = None
    error: BaseException | None = None
    metrics_history: list = field(default_factory=list)

    @property
    def best_checkpoint(self) -> Checkpoint | None:
        return self.checkpoint


class BaseTrainer:
    """Reference: train/base_trainer.py. Subclasses implement
    training_loop()."""

    def __init__(self, *, scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 resume_from_checkpoint: Checkpoint | None = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError


class DataParallelTrainer(BaseTrainer):
    """Runs train_loop_per_worker on a gang of workers; streams reports.

    Reference: train/data_parallel_trainer.py:22.
    """

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint: Checkpoint | None = None):
        super().__init__(scaling_config=scaling_config, run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.datasets = datasets or {}

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        max_failures = self.run_config.failure_config.max_failures
        storage = self.run_config.storage_path or "/tmp/ray_tpu_train"
        # Unique default name: a second-granularity timestamp collides
        # when two fits start within the same second (their checkpoint
        # managers then evict each other's checkpoints mid-run).
        name = self.run_config.name or (
            f"train_{int(time.time())}_{os.getpid()}_"
            f"{os.urandom(3).hex()}")
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            f"{storage}/{name}", num_to_keep=ckpt_cfg.num_to_keep)

        attempt = 0
        resume = self.resume_from_checkpoint
        last_error: BaseException | None = None
        all_history: list = []
        while attempt <= max(0, max_failures):
            self._before_attempt()
            try:
                result = self._run_attempt(manager, resume)
            except BaseException as exc:  # noqa: BLE001 — group formation
                result = Result(error=exc)
            all_history.extend(result.metrics_history)
            result.metrics_history = all_history
            if result.error is None:
                return result
            last_error = result.error
            resume = manager.latest_checkpoint() or resume
            attempt += 1
            logger.warning(
                "Training attempt %d failed (%r); %s", attempt, result.error,
                "restarting from last checkpoint" if attempt <= max_failures
                else "giving up")
        final = Result(error=last_error)
        final.checkpoint = manager.latest_checkpoint()
        return final

    def _before_attempt(self) -> None:
        """Hook run before each (re)start of the worker group."""

    def _run_attempt(self, manager: CheckpointManager,
                     resume: Checkpoint | None) -> Result:
        results_queue: queue.Queue = queue.Queue()
        stop_event = threading.Event()
        group = WorkerGroup(self.scaling_config)
        datasets = self.datasets
        config = dict(self.train_loop_config)
        if datasets:
            # Each worker iterates its shard (reference: data_config.py).
            config["__datasets__"] = datasets

        loop = self.train_loop_per_worker
        if datasets:
            loop = _wrap_with_datasets(loop, self.scaling_config.num_workers)

        try:
            refs = group.run(loop, config, results_queue, stop_event, resume)
            return self._collect(group, refs, results_queue, manager,
                                 stop_event)
        finally:
            group.shutdown()

    def _collect(self, group, refs, results_queue, manager,
                 stop_event) -> Result:
        n = self.scaling_config.num_workers
        done_ranks: set[int] = set()
        last_metrics: dict = {}
        history: list[dict] = []
        error: BaseException | None = None
        stop_criteria = self.run_config.stop or {}
        timeout_s = self.run_config.report_timeout_s
        pending_refs = list(refs)
        deadline = time.monotonic() + timeout_s
        while len(done_ranks) < n and error is None:
            try:
                msg = results_queue.get(timeout=1.0)
            except queue.Empty:
                # Hard worker death (process gangs) surfaces on the run
                # refs immediately — don't sit out the report timeout
                # masking the real cause.
                if pending_refs:
                    finished, pending_refs = ray_tpu.wait(
                        pending_refs, num_returns=len(pending_refs),
                        timeout=0)
                    for ref in finished:
                        try:
                            ray_tpu.get(ref)
                        except BaseException as exc:  # noqa: BLE001
                            error = exc
                            break
                if error is not None:
                    break
                if time.monotonic() > deadline:
                    error = TimeoutError(
                        f"no training report within "
                        f"report_timeout_s={timeout_s}")
                    break
                continue
            deadline = time.monotonic() + timeout_s
            if msg.get("done"):
                done_ranks.add(msg["rank"])
                if msg.get("error") is not None:
                    error = msg["error"]
                continue
            if msg["rank"] == 0:
                last_metrics = msg["metrics"]
                history.append(msg["metrics"])
                if msg.get("checkpoint") is not None:
                    manager.register(msg["checkpoint"], msg["metrics"])
                for key, threshold in stop_criteria.items():
                    if key in last_metrics and last_metrics[key] >= threshold:
                        stop_event.set()
            elif msg.get("checkpoint") is not None:
                # Non-rank-0 checkpoints are ignored (single-controller
                # jax: rank 0 saves the sharded state).
                pass
        if error is not None:
            stop_event.set()
        return Result(metrics=last_metrics, checkpoint=manager.latest_checkpoint(),
                      error=error, metrics_history=history)


def _wrap_with_datasets(loop: Callable, num_workers: int) -> Callable:
    def wrapped(config: dict):
        from ray_tpu.train.session import get_context

        datasets = config.pop("__datasets__", {})
        rank = get_context().get_world_rank()
        config["datasets"] = {
            name: ds.shard(num_workers, rank) if hasattr(ds, "shard") else ds
            for name, ds in datasets.items()
        }
        return loop(config)

    return wrapped


class JaxTrainer(DataParallelTrainer):
    """The TPU framework trainer (analogue of TorchTrainer,
    torch/torch_trainer.py:11).

    The backend hook's job in the reference is dist.init_process_group
    (torch/config.py:47-91); the JAX analogue is jax.distributed.initialize
    on multi-host. Pass ``jax_distributed_config`` (kwargs for
    ``jax.distributed.initialize``: coordinator_address, num_processes,
    process_id) to form the multi-host world on every worker; omit it for
    the single-process slice. Workers then use session.get_mesh() and the
    parallel.train_step utilities.
    """

    def __init__(self, train_loop_per_worker: Callable,
                 jax_distributed_config: "dict | str | None" = None,
                 **kwargs):
        self._auto_spmd = jax_distributed_config == "auto"
        if self._auto_spmd:
            # Multi-process SPMD gang: this driver picks the rendezvous
            # point; every worker derives process_id from its gang rank
            # (the analogue of TorchTrainer's automatic
            # init_process_group rendezvous, torch/config.py:47-91).
            from ray_tpu.train.config import ScalingConfig as _SC

            scaling = kwargs.get("scaling_config") or _SC()
            if scaling.num_workers > 1 and not scaling.use_process_workers:
                raise ValueError(
                    "jax_distributed_config='auto' with num_workers>1 "
                    "requires ScalingConfig(use_process_workers=True): "
                    "thread workers share one process and can never "
                    "form a multi-process jax.distributed world")
            jax_distributed_config = {
                "num_processes": scaling.num_workers,
            }
            self._refresh_coordinator(jax_distributed_config)
        self.jax_distributed_config = jax_distributed_config
        super().__init__(
            self._jax_backend_wrap(train_loop_per_worker,
                                   jax_distributed_config), **kwargs)

    @staticmethod
    def _refresh_coordinator(config: dict) -> None:
        import socket

        from ray_tpu._private.node import _own_address

        sock = socket.socket()
        sock.bind(("", 0))
        port = sock.getsockname()[1]
        sock.close()
        config["coordinator_address"] = f"{_own_address()}:{port}"

    def _before_attempt(self) -> None:
        # Fresh coordinator port per (re)start: the previous gang's
        # rank-0 process may still be exiting and holding the old port
        # (shutdown SIGTERMs without waiting), and EADDRINUSE would
        # burn the retry budget on an infra conflict. The loop wrapper
        # closes over this dict, so mutating it reaches the workers.
        if self._auto_spmd:
            self._refresh_coordinator(self.jax_distributed_config)

    @staticmethod
    def _jax_backend_wrap(loop: Callable,
                          dist_config: dict | None) -> Callable:
        def wrapped(config):
            import os

            import jax

            if dist_config is not None:
                from ray_tpu.train.session import get_context

                cfg = dict(dist_config)
                # process_id is per-worker: derive from the gang rank unless
                # the caller pinned it explicitly.
                cfg.setdefault("process_id", get_context().get_world_rank())
                try:
                    jax.distributed.initialize(**cfg)
                except RuntimeError as e:
                    # Tolerate ONLY double-init (workers sharing a process in
                    # the local runtime, or a restart within one process).
                    # Anything else (coordinator unreachable, deadline
                    # exceeded) must fail loudly or the gang silently
                    # trains with the wrong world size.
                    msg = str(e).lower()
                    if ("already initialized" not in msg
                            and "only be called once" not in msg):
                        raise
            elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
                # Multi-host launch configured via env (the analogue of
                # torchrun env:// rendezvous); idempotent per process.
                try:
                    jax.distributed.initialize()
                except RuntimeError as e:
                    msg = str(e).lower()
                    if ("already initialized" not in msg
                            and "only be called once" not in msg):
                        raise
            return loop(config)

        return wrapped
