"""Checkpoints: directory-based with orbax for sharded arrays.

Reference: python/ray/train/_checkpoint.py (Checkpoint = directory +
fsspec upload) and _internal/checkpoint_manager.py (top-K retention).
TPU-native: array state goes through orbax (async-capable, handles
jax.Array shardings) — SURVEY §5 "checkpoint/resume" TPU note.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import time
from typing import Any

# Orbax's tmp-directory/OCDBT machinery is not safe for CONCURRENT
# saves from multiple threads of one process (observed: rmtree races in
# atomicity._create_tmp_directory when two thread-mode gang workers
# checkpoint simultaneously). Serialize in-process saves; separate
# processes (real multi-host) are unaffected.
_ORBAX_SAVE_LOCK = threading.Lock()


class Checkpoint:
    """A directory of checkpoint data."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        tmp = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(tmp, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(tmp)

    def to_dict(self) -> dict:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def as_directory(self) -> str:
        return self.path

    # ---- jax pytree state (orbax when available, pickle fallback) ----

    @classmethod
    def from_state(cls, state: Any, path: str | None = None) -> "Checkpoint":
        """Save a pytree of (possibly sharded) jax arrays."""
        target = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(target, exist_ok=True)
        try:
            import orbax.checkpoint as ocp
        except ImportError:
            ocp = None
        if ocp is not None:
            # Real save failures (disk full, bad pytree leaf) must surface,
            # not silently change the on-disk format — only an unavailable
            # orbax triggers the pickle fallback.
            with _ORBAX_SAVE_LOCK:
                ckptr = ocp.StandardCheckpointer()
                ckptr.save(os.path.join(target, "state"), state,
                           force=True)
                ckptr.wait_until_finished()
            meta = {"format": "orbax"}
        else:
            import jax
            import numpy as np

            host_state = jax.tree.map(
                lambda x: np.asarray(x) if hasattr(x, "dtype") else x, state)
            with open(os.path.join(target, "state.pkl"), "wb") as f:
                pickle.dump(host_state, f)
            meta = {"format": "pickle"}
        with open(os.path.join(target, "meta.json"), "w") as f:
            json.dump(meta, f)
        return cls(target)

    def to_state(self, template: Any | None = None) -> Any:
        with open(os.path.join(self.path, "meta.json")) as f:
            meta = json.load(f)
        if meta["format"] == "orbax":
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            return ckptr.restore(os.path.join(self.path, "state"), template)
        with open(os.path.join(self.path, "state.pkl"), "rb") as f:
            return pickle.load(f)

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Top-K checkpoint retention (reference:
    train/_internal/checkpoint_manager.py)."""

    def __init__(self, storage_path: str, num_to_keep: int | None = None,
                 metric: str | None = None, mode: str = "max"):
        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self.metric = metric
        self.mode = mode
        # (score, seq, path, metrics); seq is a monotonic counter so names
        # never collide (timestamps alone can repeat within a millisecond)
        # and "latest" is insertion order, not lexicographic path order.
        self._entries: list[tuple[float, int, str, dict]] = []
        self._seq = 0
        os.makedirs(storage_path, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: dict) -> str:
        """Move a checkpoint into managed storage; evict beyond top-K."""
        seq = self._seq
        self._seq += 1
        name = f"checkpoint_{int(time.time() * 1000):x}_{seq:08d}"
        dest = os.path.join(self.storage_path, name)
        if os.path.abspath(checkpoint.path) != os.path.abspath(dest):
            shutil.move(checkpoint.path, dest)
        score = metrics.get(self.metric, 0.0) if self.metric else float(seq)
        if self.mode == "min":
            score = -score
        self._entries.append((score, seq, dest, dict(metrics)))
        self._entries.sort(key=lambda e: (e[0], e[1]), reverse=True)
        if self.num_to_keep is not None:
            while len(self._entries) > self.num_to_keep:
                _, _, evict_path, _ = self._entries.pop()
                shutil.rmtree(evict_path, ignore_errors=True)
        return dest

    def best_checkpoint(self) -> Checkpoint | None:
        if not self._entries:
            return None
        return Checkpoint(self._entries[0][2])

    def latest_checkpoint(self) -> Checkpoint | None:
        if not self._entries:
            return None
        latest = max(self._entries, key=lambda e: e[1])
        return Checkpoint(latest[2])
