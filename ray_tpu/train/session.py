"""Worker-side training session: report/get_context/get_checkpoint.

Reference: python/ray/train/_internal/session.py — _TrainSession (:109),
report (:394/:654), get_checkpoint (:741), get_context (context.py:80).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any

from ray_tpu.train.checkpoint import Checkpoint


class StopTraining(Exception):
    """Raised inside the train loop when the controller stops the trial."""


@dataclass
class TrainContext:
    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_name: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank


@dataclass
class _SessionState:
    context: TrainContext
    results_queue: Any  # queue.Queue shared with the executor
    resume_checkpoint: Checkpoint | None = None
    stop_event: threading.Event = field(default_factory=threading.Event)
    iteration: int = 0


class _TrainSession:
    _tls = threading.local()

    @classmethod
    def current(cls) -> _SessionState | None:
        return getattr(cls._tls, "state", None)

    @classmethod
    def set(cls, state: _SessionState | None):
        cls._tls.state = state


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    """Stream metrics (and optionally a checkpoint) back to the driver.

    Reference: ray.train.report (session.py:654). If the controller has
    requested a stop (e.g. ASHA early termination), raises StopTraining.
    """
    state = _TrainSession.current()
    if state is None:
        raise RuntimeError("report() called outside a training session")
    state.iteration += 1
    state.results_queue.put({
        "rank": state.context.world_rank,
        "iteration": state.iteration,
        "metrics": dict(metrics),
        "checkpoint": checkpoint,
        "done": False,
    })
    if state.stop_event.is_set():
        raise StopTraining()


def get_context() -> TrainContext:
    state = _TrainSession.current()
    if state is None:
        return TrainContext()
    return state.context


def get_checkpoint() -> Checkpoint | None:
    """The checkpoint to resume from (reference: session.py:741)."""
    state = _TrainSession.current()
    return state.resume_checkpoint if state is not None else None


def run_with_session(fn, config, state: _SessionState, emit) -> Any:
    """Run ``fn(config)`` under a session; emit({...}) reports completion.

    Shared by train workers and tune trials so the report/StopTraining/
    error protocol lives in exactly one place. ``config`` is shallow-
    copied: the in-process runtime passes task args by reference, so
    without the copy every gang member would share (and mutate) one dict.
    """
    _TrainSession.set(state)
    try:
        result = fn(dict(config)) if config is not None else fn()
        emit({"done": True, "result": result, "error": None})
        return result
    except StopTraining:
        emit({"done": True, "result": None, "error": None})
        return None
    except BaseException as exc:  # noqa: BLE001 — surfaced to the driver
        import traceback

        # The driver only sees the exception object; keep the worker
        # traceback attached or failures are undebuggable.
        try:
            exc.__ray_tpu_remote_tb__ = "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__))
        except Exception:
            pass  # tb attach is best-effort on exotic excs
        emit({"done": True, "result": None, "error": exc})
        raise
    finally:
        _TrainSession.set(None)


def get_mesh(config=None):
    """Convenience: build the device mesh for this worker group.

    In the single-controller JAX model the *whole worker group* is the
    SPMD unit (SURVEY §7 hard parts): every worker enters the same jitted
    program, so the mesh spans all devices jax can see.
    """
    from ray_tpu.parallel.mesh import MeshConfig, build_mesh

    return build_mesh(config or MeshConfig(dp=-1))
