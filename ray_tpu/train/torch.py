"""TorchTrainer — data-parallel torch training on the worker group.

Reference: python/ray/train/torch/ (TorchTrainer torch_trainer.py:11;
_TorchBackend config.py:129 calls dist.init_process_group(nccl|gloo);
prepare_model train_loop_utils.py:158 wraps DDP; prepare_data_loader
:200 adds a DistributedSampler).

TPU-native departure: instead of forming a torch.distributed process
group (NCCL/gloo — the reference's comm plane), gradient synchronization
rides the framework's OWN host collective (util.collective store-side
allreduce). That keeps the trainer comm-backend-free: the same loop
runs on thread or process workers, and on TPU fleets where NCCL does
not exist. ``prepare_model`` still gives DDP semantics — params
broadcast from rank 0 at wrap time, gradients averaged across ranks on
``backward()`` via per-parameter post-accumulate hooks.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Callable

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import DataParallelTrainer

# Which collective group THIS worker thread's trainer run uses; set by
# the backend wrap so prepare_model/prepare_data_loader can find it
# without threading a handle through user code (thread actors => one
# training loop per thread).
_tls = threading.local()


class TorchTrainer(DataParallelTrainer):
    """Reference: torch/torch_trainer.py:11 — DataParallelTrainer with
    the torch backend; here the backend is the framework collective."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint=None):
        super().__init__(
            self._torch_backend_wrap(train_loop_per_worker,
                                     scaling_config),
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )

    @staticmethod
    def _torch_backend_wrap(loop: Callable,
                            scaling: ScalingConfig | None) -> Callable:
        # Unique per trainer INSTANCE so concurrent fits (e.g. under
        # tune) never share a rendezvous store.
        group = f"__torch_trainer__{uuid.uuid4().hex[:8]}"

        def wrapped(config: dict):
            from ray_tpu.train.session import get_context
            from ray_tpu.util import collective

            ctx = get_context()
            world = ctx.get_world_size()
            _tls.group = group
            if world > 1:
                # The collective group is the torch "process group"
                # (reference: _TorchBackend.on_start init_process_group).
                collective.init_collective_group(
                    world, ctx.get_world_rank(), group_name=group)
            try:
                return loop(config)
            finally:
                _tls.group = None
                if world > 1:
                    collective.destroy_collective_group(group)

        return wrapped


def _group_name() -> str:
    group = getattr(_tls, "group", None)
    if not group:
        raise RuntimeError(
            "prepare_model/prepare_data_loader must run inside a "
            "TorchTrainer training loop")
    return group


def prepare_model(model) -> Any:
    """DDP-equivalent wrap (reference: train_loop_utils.py:158).

    - broadcasts rank 0's parameters and buffers so every rank starts
      identical;
    - registers post-accumulate-grad hooks that allreduce-average each
      parameter's gradient across ranks on ``loss.backward()``.

    Hook ordering note: the collective store matches contributions by
    per-group op sequence; autograd fires the hooks in reverse graph
    order, identical on every rank for identical models, so sequence
    numbers line up without a torch bucketing layer.
    """
    import torch

    from ray_tpu.train.session import get_context
    from ray_tpu.util import collective

    ctx = get_context()
    world = ctx.get_world_size()
    if world <= 1:
        return model

    group = _group_name()
    with torch.no_grad():
        for tensor in list(model.parameters()) + list(model.buffers()):
            synced = collective.broadcast(
                tensor.detach().cpu().numpy(), src_rank=0,
                group_name=group)
            tensor.copy_(torch.as_tensor(synced).to(tensor.dtype))

    def make_hook():
        def hook(param):
            if param.grad is None:
                return
            reduced = collective.allreduce(
                param.grad.detach().cpu().numpy(), group_name=group)
            param.grad.copy_(
                torch.as_tensor(reduced / world).to(param.grad.dtype))

        return hook

    for param in model.parameters():
        if param.requires_grad:
            param.register_post_accumulate_grad_hook(make_hook())
    return model


class _EpochShardedLoader:
    """DataLoader wrapper that advances its DistributedSampler epoch on
    every iteration (the reference documents users must call
    ``sampler.set_epoch``; hiding the sampler means we must do it, or
    every epoch replays one permutation)."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0
        self.batch_size = loader.batch_size
        self.dataset = loader.dataset

    def __iter__(self):
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)


def prepare_data_loader(data_loader):
    """Shard a DataLoader across ranks (reference:
    train_loop_utils.py:200 adds DistributedSampler). Preserves the
    caller's shuffle choice and reshuffles per epoch when shuffling."""
    import torch

    from ray_tpu.train.session import get_context

    ctx = get_context()
    world = ctx.get_world_size()
    if world <= 1:
        return data_loader
    # Respect the original ordering intent: a RandomSampler means the
    # caller asked for shuffle=True; anything else stays ordered.
    shuffle = isinstance(getattr(data_loader, "sampler", None),
                         torch.utils.data.RandomSampler)
    sampler = torch.utils.data.distributed.DistributedSampler(
        data_loader.dataset, num_replicas=world,
        rank=ctx.get_world_rank(), shuffle=shuffle)
    loader = torch.utils.data.DataLoader(
        data_loader.dataset, batch_size=data_loader.batch_size,
        sampler=sampler, num_workers=0,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last)
    if not shuffle:
        return loader
    return _EpochShardedLoader(loader, sampler)


def backward_sync_disabled(model):
    """Context manager: skip gradient sync (reference: DDP.no_sync for
    gradient accumulation) — implemented by removing nothing; callers
    accumulate with hooks firing each backward, so emulate no_sync by
    scaling: not supported, raise with guidance."""
    raise NotImplementedError(
        "gradient accumulation with deferred sync is not supported; "
        "accumulate in the loss (sum microbatches) instead")
