"""ray_tpu.train — distributed training orchestration (reference:
python/ray/train)."""

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    get_checkpoint,
    get_context,
    get_mesh,
    report,
)
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
    Result,
)
from ray_tpu.train.huggingface import (
    TransformersTrainer,
    causal_lm_loss_fn,
    make_transformers_train_loop,
)
from ray_tpu.train.torch import TorchTrainer

__all__ = [
    "BaseTrainer",
    "TorchTrainer",
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxTrainer",
    "TransformersTrainer",
    "causal_lm_loss_fn",
    "make_transformers_train_loop",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "get_checkpoint",
    "get_context",
    "get_mesh",
    "report",
]
