"""ray_tpu.train — distributed training orchestration (reference:
python/ray/train)."""

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    get_checkpoint,
    get_context,
    get_mesh,
    report,
)
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
    Result,
)
from ray_tpu.train.torch import TorchTrainer

__all__ = [
    "BaseTrainer",
    "TorchTrainer",
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "get_checkpoint",
    "get_context",
    "get_mesh",
    "report",
]
