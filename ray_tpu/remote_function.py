"""@remote function frontend.

Reference: python/ray/remote_function.py:40 (RemoteFunction, _remote at
:268) and option handling in python/ray/_private/ray_option_utils.py.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.task import SchedulingStrategy, normalize_resources

_VALID_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "num_returns",
    "max_retries", "retry_exceptions", "name", "scheduling_strategy",
    "placement_group", "placement_group_bundle_index", "runtime_env",
    "memory", "max_calls", "_metadata", "_deadline_s",
}


def _build_strategy(options: dict) -> SchedulingStrategy:
    strategy = options.get("scheduling_strategy")
    if isinstance(strategy, SchedulingStrategy):
        return strategy
    if strategy == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if strategy == "DEFAULT" or strategy is None:
        pg = options.get("placement_group")
        if pg is not None:
            return SchedulingStrategy(
                kind="PLACEMENT_GROUP", placement_group=pg,
                placement_group_bundle_index=options.get(
                    "placement_group_bundle_index", -1))
        return SchedulingStrategy()
    # Library scheduling-strategy dataclasses.
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP", placement_group=strategy.placement_group,
            placement_group_bundle_index=strategy.placement_group_bundle_index)
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return SchedulingStrategy(
            kind="NODE_AFFINITY", node_id=strategy.node_id, soft=strategy.soft)
    raise ValueError(f"Unsupported scheduling_strategy: {strategy!r}")


class RemoteFunction:
    """A function turned into a task factory via ``@ray_tpu.remote``."""

    def __init__(self, func: Callable, default_options: dict | None = None):
        self._function = func
        self._default_options = dict(default_options or {})
        bad = set(self._default_options) - _VALID_OPTIONS
        if bad:
            raise ValueError(f"Invalid @remote options: {sorted(bad)}")
        # The per-call submit arguments are pure functions of the
        # options, which are frozen per RemoteFunction instance
        # (.options() builds a NEW instance) — precompute them once so
        # a 100k-submit burst doesn't re-derive resources/strategy/name
        # per call. The strategy object is shared across calls: specs
        # only ever read it.
        opts = self._default_options
        self._call_kwargs = dict(
            name=opts.get("name") or func.__qualname__,
            num_returns=opts.get("num_returns", 1),
            resources=normalize_resources(
                opts.get("num_cpus"),
                opts.get("num_tpus") or opts.get("num_gpus"),
                opts.get("resources"),
            ),
            max_retries=opts.get("max_retries", 0),
            retry_exceptions=opts.get("retry_exceptions", False),
            scheduling_strategy=_build_strategy(opts),
            runtime_env=opts.get("runtime_env"),
            deadline_s=opts.get("_deadline_s"),
        )
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._function.__name__}' cannot be called "
            "directly. Use '.remote()' to submit it as a task, or access the "
            "underlying function via '.func'.")

    @property
    def func(self) -> Callable:
        return self._function

    def options(self, **options) -> "RemoteFunction":
        bad = set(options) - _VALID_OPTIONS
        if bad:
            raise ValueError(f"Invalid options: {sorted(bad)}")
        merged = {**self._default_options, **options}
        return RemoteFunction(self._function, merged)

    def remote(self, *args, _deadline_s: float | None = None, **kwargs):
        """``_deadline_s`` arms an end-to-end deadline for THIS call
        (overrides the @remote/options default): the task must seal a
        result within the budget or its refs raise TaskTimeoutError —
        checked at every pipeline stage, never executed once dead."""
        runtime = worker_mod.auto_init()
        call_kwargs = self._call_kwargs
        if _deadline_s is not None:
            call_kwargs = {**call_kwargs, "deadline_s": _deadline_s}
        refs = runtime.submit_task(self._function, args, kwargs,
                                   **call_kwargs)
        if call_kwargs["num_returns"] == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: ray.dag — fn.bind(...).execute())."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __repr__(self):
        return f"RemoteFunction({self._function.__qualname__})"
