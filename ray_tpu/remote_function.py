"""@remote function frontend.

Reference: python/ray/remote_function.py:40 (RemoteFunction, _remote at
:268) and option handling in python/ray/_private/ray_option_utils.py.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

from ray_tpu._private import dispatch_lanes
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.task import SchedulingStrategy, normalize_resources

_VALID_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "num_returns",
    "max_retries", "retry_exceptions", "name", "scheduling_strategy",
    "placement_group", "placement_group_bundle_index", "runtime_env",
    "memory", "max_calls", "_metadata", "_deadline_s",
}


def _build_strategy(options: dict) -> SchedulingStrategy:
    strategy = options.get("scheduling_strategy")
    if isinstance(strategy, SchedulingStrategy):
        return strategy
    if strategy == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if strategy == "DEFAULT" or strategy is None:
        pg = options.get("placement_group")
        if pg is not None:
            return SchedulingStrategy(
                kind="PLACEMENT_GROUP", placement_group=pg,
                placement_group_bundle_index=options.get(
                    "placement_group_bundle_index", -1))
        return SchedulingStrategy()
    # Library scheduling-strategy dataclasses.
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP", placement_group=strategy.placement_group,
            placement_group_bundle_index=strategy.placement_group_bundle_index)
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return SchedulingStrategy(
            kind="NODE_AFFINITY", node_id=strategy.node_id, soft=strategy.soft)
    raise ValueError(f"Unsupported scheduling_strategy: {strategy!r}")


class RemoteFunction:
    """A function turned into a task factory via ``@ray_tpu.remote``."""

    def __init__(self, func: Callable, default_options: dict | None = None):
        self._function = func
        self._default_options = dict(default_options or {})
        bad = set(self._default_options) - _VALID_OPTIONS
        if bad:
            raise ValueError(f"Invalid @remote options: {sorted(bad)}")
        # The per-call submit arguments are pure functions of the
        # options, which are frozen per RemoteFunction instance
        # (.options() builds a NEW instance) — precompute them once so
        # a 100k-submit burst doesn't re-derive resources/strategy/name
        # per call. The strategy object is shared across calls: specs
        # only ever read it.
        opts = self._default_options
        self._call_kwargs = dict(
            name=opts.get("name") or func.__qualname__,
            num_returns=opts.get("num_returns", 1),
            resources=normalize_resources(
                opts.get("num_cpus"),
                opts.get("num_tpus") or opts.get("num_gpus"),
                opts.get("resources"),
            ),
            max_retries=opts.get("max_retries", 0),
            retry_exceptions=opts.get("retry_exceptions", False),
            scheduling_strategy=_build_strategy(opts),
            runtime_env=opts.get("runtime_env"),
            deadline_s=opts.get("_deadline_s"),
        )
        # Columnar submit template (ISSUE 15): frozen once per
        # RemoteFunction for DEFAULT-strategy, single-return,
        # env-free, deadline-free, non-TPU functions — the sharded
        # dispatch fast path slices per-call columns off it instead of
        # building a TaskSpec per submit. None = never eligible.
        self._col_template = None
        ck = self._call_kwargs
        strategy = ck["scheduling_strategy"]
        if (ck["num_returns"] == 1 and ck["runtime_env"] is None
                and ck["deadline_s"] is None
                and strategy.kind == "DEFAULT"
                and getattr(strategy, "placement_group", None) is None
                and not any(k.startswith("TPU")
                            for k in ck["resources"])):
            self._col_template = dispatch_lanes.ColumnarTemplate(
                func, ck["name"], ck["resources"], ck["max_retries"],
                ck["retry_exceptions"], strategy)
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._function.__name__}' cannot be called "
            "directly. Use '.remote()' to submit it as a task, or access the "
            "underlying function via '.func'.")

    @property
    def func(self) -> Callable:
        return self._function

    def options(self, **options) -> "RemoteFunction":
        bad = set(options) - _VALID_OPTIONS
        if bad:
            raise ValueError(f"Invalid options: {sorted(bad)}")
        merged = {**self._default_options, **options}
        return RemoteFunction(self._function, merged)

    def remote(self, *args, _deadline_s: float | None = None, **kwargs):
        """``_deadline_s`` arms an end-to-end deadline for THIS call
        (overrides the @remote/options default): the task must seal a
        result within the budget or its refs raise TaskTimeoutError —
        checked at every pipeline stage, never executed once dead."""
        runtime = worker_mod.auto_init()
        template = self._col_template
        if (template is not None and _deadline_s is None and not kwargs
                and dispatch_lanes.SHARD_ON
                and runtime.__class__ is worker_mod.Runtime
                and not GLOBAL_CONFIG.peek("task_default_deadline_s")):
            # Columnar fast path: one buffer append instead of a
            # _SubmitRecord + ring push; falls through (None) for
            # ineligible args or when the lanes aren't running.
            ref = runtime.submit_columnar(template, args)
            if ref is not None:
                return ref
        call_kwargs = self._call_kwargs
        if _deadline_s is not None:
            call_kwargs = {**call_kwargs, "deadline_s": _deadline_s}
        refs = runtime.submit_task(self._function, args, kwargs,
                                   **call_kwargs)
        if call_kwargs["num_returns"] == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: ray.dag — fn.bind(...).execute())."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __repr__(self):
        return f"RemoteFunction({self._function.__qualname__})"
