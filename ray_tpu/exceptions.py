"""User-visible exceptions.

Mirrors the reference's exception taxonomy (reference:
python/ray/exceptions.py): task errors wrap the user exception with the
remote traceback, actor errors/actor-death, object loss, and timeouts.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    The original exception is available as ``.cause``; re-raising through
    ``get()`` chains the remote traceback text so users see where the
    failure happened (reference: python/ray/exceptions.py RayTaskError).
    """

    def __init__(self, cause: BaseException, remote_traceback: str = "",
                 task_name: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_name = task_name
        super().__init__(str(cause))

    def __str__(self):
        base = f"Task '{self.task_name}' failed: {type(self.cause).__name__}: {self.cause}"
        if self.remote_traceback:
            base += "\n\nRemote traceback:\n" + self.remote_traceback
        return base


class ActorError(TaskError):
    """An actor method raised an exception."""


class ActorDiedError(RayTpuError):
    """The actor was dead when a method call was attempted."""

    def __init__(self, actor_id=None, reason: str = "actor has died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(reason)


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """An object could not be found in any store and had no lineage."""

    def __init__(self, object_ref=None, reason: str = "object lost"):
        self.object_ref = object_ref
        super().__init__(reason)


class ObjectFreedError(ObjectLostError):
    """The object was explicitly freed."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get()`` did not complete within the requested timeout."""


class TaskTimeoutError(TaskError):
    """The task's end-to-end deadline expired before it produced a
    result. Sealed onto the task's return refs by whichever pipeline
    stage found the budget dead (``.stage``: submit / queued / dispatch
    / admitted / worker / execute / actor_queue), so ``get()`` raises
    it instead of executing dead work. NOT retryable by the runtime —
    the deadline belongs to the caller; resubmit with a fresh budget.
    """

    def __init__(self, task_name: str = "", stage: str = "",
                 deadline: float = 0.0):
        self.stage = stage
        self.deadline = deadline
        cause = TimeoutError(
            f"end-to-end deadline expired at stage {stage!r}")
        super().__init__(cause, "", task_name)

    def __reduce__(self):
        # TaskError's base reduce re-calls __init__ with the formatted
        # message; this subclass takes different args and must round-
        # trip through store seals and RPC error blobs.
        return (TaskTimeoutError,
                (self.task_name, self.stage, self.deadline))


class SystemOverloadedError(RayTpuError):
    """Admission control rejected the work instead of queueing it
    unboundedly (queue-depth cap, memory watermark, or a serve tier at
    ``max_queued_requests``). RETRYABLE: nothing executed — back off
    and resubmit (the HTTP tier maps this to a 503)."""

    def __init__(self, reason: str = "system overloaded",
                 retry_after_s: float = 0.1):
        self.retry_after_s = retry_after_s
        super().__init__(reason)

    def __reduce__(self):
        return (SystemOverloadedError,
                (self.args[0] if self.args else "system overloaded",
                 self.retry_after_s))


class CacheExhaustedError(SystemOverloadedError):
    """The LLM engine's paged KV-cache (or its bounded waiting queue)
    cannot hold this request right now. Subclasses
    ``SystemOverloadedError`` so it sheds through the existing typed
    overload path (serve handle callers see it typed; the HTTP tier
    maps it to 503 + Retry-After). RETRYABLE: nothing decoded — back
    off and resubmit."""

    def __init__(self, reason: str = "KV cache exhausted",
                 retry_after_s: float = 0.5):
        super().__init__(reason, retry_after_s)

    def __reduce__(self):
        return (CacheExhaustedError,
                (self.args[0] if self.args else "KV cache exhausted",
                 self.retry_after_s))


class TaskCancelledError(RayTpuError):
    """The task was cancelled before or during execution."""

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__("task was cancelled")


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's pending call queue exceeded max_pending_calls."""


class WorkerCrashedError(RayTpuError):
    """A worker process died while executing a task (system failure —
    retried when retries remain, reference: python/ray/exceptions.py
    WorkerCrashedError)."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a task/actor."""


class OutOfMemoryError(RayTpuError):
    """The object store or worker heap exceeded its memory budget."""


class PlacementGroupError(RayTpuError):
    """Placement group creation/scheduling failed."""
