"""ray_tpu CLI — cluster lifecycle + job submission.

Reference: python/ray/scripts/scripts.py (``ray start``:566, ``ray
stop``:1042, ``ray status``, ``ray job ...`` via
dashboard/modules/job/cli.py). Usage::

    python -m ray_tpu start --head [--port 6379]
    python -m ray_tpu start --address HOST:PORT        # join as worker
    python -m ray_tpu status [--address HOST:PORT]
    python -m ray_tpu stop
    python -m ray_tpu job submit [--address A] -- python script.py
    python -m ray_tpu job {status,logs,stop} SUBMISSION_ID
    python -m ray_tpu job list
    python -m ray_tpu list {tasks,actors,objects,nodes,...}  # state CLI
    python -m ray_tpu summary [tasks|placement]  # per-function latency/
                                    # resources + per-node placement/load
    python -m ray_tpu top              # live per-node rates + verdicts
    python -m ray_tpu doctor           # one-shot health verdict report
    python -m ray_tpu up cluster.yaml                  # YAML launcher
    python -m ray_tpu down cluster.yaml
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

SESSION_DIR = os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")


def _pidfile(role: str) -> str:
    return os.path.join(SESSION_DIR, f"{role}.pid")


def _head_address_file() -> str:
    return os.path.join(SESSION_DIR, "head_address")


def resolve_address(address: str | None) -> str:
    """CLI --address, RAY_TPU_ADDRESS env, or the local head's file."""
    if address:
        return address
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    try:
        with open(_head_address_file()) as f:
            return f.read().strip()
    except FileNotFoundError:
        raise SystemExit(
            "no cluster address: pass --address, set RAY_TPU_ADDRESS, or "
            "start a head on this machine (python -m ray_tpu start --head)")


def _spawn_daemon(role: str, kwargs: dict) -> int:
    os.makedirs(SESSION_DIR, exist_ok=True)
    log = open(os.path.join(SESSION_DIR, f"{role}.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node", role,
         json.dumps(kwargs)],
        stdout=log, stderr=subprocess.STDOUT, start_new_session=True)
    with open(_pidfile(role), "w") as f:
        f.write(str(proc.pid))
    return proc.pid


def cmd_start(args) -> int:
    from ray_tpu._private.rpc import RpcClient

    if args.head:
        pid = _spawn_daemon("head", {"port": args.port})
        # Wait for the head to publish its address.
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                with open(_head_address_file()) as f:
                    address = f.read().strip()
                if address and RpcClient(address, timeout_s=2).ping():
                    print(f"ray_tpu head started (pid {pid}) at {address}")
                    print(f"  connect workers:  python -m ray_tpu start "
                          f"--address {address}")
                    print(f"  submit jobs:      python -m ray_tpu job "
                          f"submit --address {address} -- <cmd>")
                    return 0
            except (FileNotFoundError, OSError):
                pass  # head not up yet: keep polling
            time.sleep(0.2)
        print("head failed to start; see "
              f"{os.path.join(SESSION_DIR, 'head.log')}", file=sys.stderr)
        return 1
    if not args.address:
        print("start requires --head or --address", file=sys.stderr)
        return 1
    resources = {}
    if args.num_cpus is not None:
        resources["CPU"] = float(args.num_cpus)
    pid = _spawn_daemon("worker", {
        "gcs_address": args.address,
        "resources": resources or None})
    print(f"ray_tpu worker started (pid {pid}), joining {args.address}")
    return 0


def cmd_stop(args) -> int:
    stopped = 0
    for role in ("worker", "head"):
        path = _pidfile(role)
        try:
            with open(path) as f:
                pid = int(f.read().strip())
        except (FileNotFoundError, ValueError):
            continue
        try:
            os.kill(pid, signal.SIGTERM)
            stopped += 1
            print(f"stopped {role} (pid {pid})")
        except ProcessLookupError:
            pass
        os.remove(path)
    if stopped == 0:
        print("no ray_tpu daemons found")
    return 0


def cmd_status(args) -> int:
    from ray_tpu._private.rpc import RpcClient, RpcError

    address = resolve_address(args.address)
    client = RpcClient(address, timeout_s=5)
    try:
        nodes = client.call("list_nodes")
        resources = client.call("cluster_resources")
        jobs = client.call("list_jobs")
    except RpcError as exc:
        print(f"cannot reach GCS at {address}: {exc}", file=sys.stderr)
        return 1
    alive = [n for n in nodes if n["alive"]]
    print(f"cluster at {address}: {len(alive)} alive node(s), "
          f"{len(nodes) - len(alive)} dead")
    for n in nodes:
        state = "ALIVE" if n["alive"] else "DEAD"
        role = n["labels"].get("node_role", "?")
        avail = n.get("available") or {}
        res = " ".join(
            f"{k}={avail[k]:g}/{v:g}" if k in avail else f"{k}={v:g}"
            for k, v in sorted(n["resources"].items()))
        print(f"  {state:<5} {role:<6} {n['node_id'][:12]}  {res}")
    print("total resources: " + " ".join(
        f"{k}={v:g}" for k, v in sorted(resources.items())))
    running = [j for j in jobs if j and j["status"] == "RUNNING"]
    if running:
        print(f"jobs running: {len(running)}")
    return 0


def cmd_job(args) -> int:
    from ray_tpu._private.rpc import RpcClient, RpcError

    address = resolve_address(args.address)
    client = RpcClient(address, timeout_s=10)
    try:
        if args.job_cmd == "submit":
            import shlex

            # shlex.join preserves each token through the head's shell.
            entrypoint = shlex.join(args.entrypoint)
            if not entrypoint:
                print("job submit requires an entrypoint after --",
                      file=sys.stderr)
                return 1
            env = {}
            env["RAY_TPU_ADDRESS"] = address
            # Client-generated id makes the RPC idempotent under the
            # client's transparent reconnect/resend.
            sub_id_req = f"raysubmit_{os.urandom(6).hex()}"
            if args.working_dir:
                sub_id = client.call(
                    "submit_job", entrypoint, env=env,
                    submission_id=sub_id_req,
                    cwd=os.path.abspath(args.working_dir))
            else:
                sub_id = client.call("submit_job", entrypoint, env=env,
                                     submission_id=sub_id_req)
            print(sub_id)
            return 0
        if args.job_cmd == "status":
            status = client.call("job_status", args.submission_id)
            if status is None:
                print(f"no such job: {args.submission_id}",
                      file=sys.stderr)
                return 1
            print(json.dumps(status, indent=2))
            return 0
        if args.job_cmd == "logs":
            sys.stdout.buffer.write(
                client.call("job_logs", args.submission_id))
            return 0
        if args.job_cmd == "stop":
            ok = client.call("stop_job", args.submission_id)
            print("stopped" if ok else "not running")
            return 0
        if args.job_cmd == "list":
            for status in client.call("list_jobs"):
                if status:
                    print(f"{status['submission_id']:<26} "
                          f"{status['status']:<10} {status['entrypoint']}")
            return 0
    except RpcError as exc:
        print(f"cannot reach GCS at {address}: {exc}", file=sys.stderr)
        return 1
    return 1


def cmd_up(args) -> int:
    from ray_tpu.autoscaler.commands import create_or_update_cluster

    state = create_or_update_cluster(args.config)
    print(f"cluster {state['cluster_name']!r} up: "
          f"head {state['head_address']} (pid {state['head_pid']}), "
          f"{len(state['workers'])} worker daemon(s)")
    print(f"  connect: ray_tpu.init(address={state['head_address']!r})")
    return 0


def cmd_down(args) -> int:
    from ray_tpu.autoscaler.commands import (
        load_cluster_config,
        teardown_cluster,
    )

    name = load_cluster_config(args.config)["cluster_name"]
    n = teardown_cluster(args.config)
    print(f"cluster {name!r}: stopped {n} process(es)")
    return 0


def cmd_serve(args) -> int:
    """`serve deploy/status/shutdown`: the declarative config path
    (reference: `serve deploy` against ServeDeploySchema,
    serve/schema.py:701).

    The deploying process OWNS the serve app: the controller actor and
    the HTTP proxy live in it (same lifecycle as `serve.run` in a
    driver script). `deploy --blocking` keeps the process alive to
    serve; without it the deploy is only useful for smoke-checking the
    config against a cluster."""
    import json as _json

    import ray_tpu
    from ray_tpu import serve

    if args.address:
        ray_tpu.init(address=args.address, num_cpus=0,
                     ignore_reinit_error=True)
    else:
        ray_tpu.init(ignore_reinit_error=True)
    if args.serve_cmd == "deploy":
        from ray_tpu.serve.schema import ServeDeployConfig, deploy_config

        names = deploy_config(ServeDeployConfig.from_yaml(args.config))
        print(f"deployed application(s): {', '.join(names)}")
        if getattr(args, "blocking", False):
            print("serving (ctrl-c to stop)")
            import time as _time

            try:
                while True:
                    _time.sleep(1)
            except KeyboardInterrupt:
                serve.shutdown()
        return 0
    if args.serve_cmd == "status":
        print(_json.dumps(serve.status(), indent=2, default=str))
        return 0
    if args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")
        return 0
    return 2


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # `list ...` routes to the state CLI (ray_tpu/util/state);
    # `summary` (per-function latency/resource percentiles), `debug`
    # (flight-recorder post-mortem bundle), `top` (live per-node rate
    # view over the history plane) and `doctor` (one-shot watchdog
    # verdict report) live there too.
    if argv and argv[0] in ("list", "summary", "timeline", "debug",
                            "top", "doctor"):
        from ray_tpu.util.state.api import _cli

        return _cli(argv)

    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_start = sub.add_parser("start", help="start a head or worker daemon")
    p_start.add_argument("--head", action="store_true")
    p_start.add_argument("--port", type=int, default=6379)
    p_start.add_argument("--address", help="head GCS address (worker mode)")
    p_start.add_argument("--num-cpus", type=float, default=None)
    p_start.set_defaults(fn=cmd_start)

    p_stop = sub.add_parser("stop", help="stop local daemons")
    p_stop.set_defaults(fn=cmd_stop)

    p_status = sub.add_parser("status", help="show cluster nodes/resources")
    p_status.add_argument("--address", default=None)
    p_status.set_defaults(fn=cmd_status)

    p_up = sub.add_parser(
        "up", help="create/update a cluster from a YAML config")
    p_up.add_argument("config")
    p_up.set_defaults(fn=cmd_up)

    p_down = sub.add_parser(
        "down", help="tear down a YAML-launched cluster")
    p_down.add_argument("config")
    p_down.set_defaults(fn=cmd_down)

    p_serve = sub.add_parser(
        "serve", help="declarative Serve deploy/status/shutdown")
    ssub = p_serve.add_subparsers(dest="serve_cmd", required=True)
    p_sdeploy = ssub.add_parser("deploy")
    p_sdeploy.add_argument("config", help="YAML app config")
    p_sdeploy.add_argument("--address", default=None)
    p_sdeploy.add_argument(
        "--blocking", action="store_true",
        help="stay alive and serve (the deploying process owns the "
             "controller and HTTP proxy)")
    for sname in ("status", "shutdown"):
        p = ssub.add_parser(sname)
        p.add_argument("--address", default=None)
    p_serve.set_defaults(fn=cmd_serve)

    p_job = sub.add_parser("job", help="job submission API")
    jsub = p_job.add_subparsers(dest="job_cmd", required=True)
    p_submit = jsub.add_parser("submit")
    p_submit.add_argument("--address", default=None)
    p_submit.add_argument("--working-dir", default=None)
    p_submit.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        p = jsub.add_parser(name)
        p.add_argument("submission_id")
        p.add_argument("--address", default=None)
    p_list = jsub.add_parser("list")
    p_list.add_argument("--address", default=None)
    p_job.set_defaults(fn=cmd_job)

    args = parser.parse_args(argv)
    # Strip the leading "--" separator from a REMAINDER entrypoint.
    entry = getattr(args, "entrypoint", None)
    if entry and entry[0] == "--":
        args.entrypoint = entry[1:]
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
