"""Multi-daemon test cluster on one machine.

Reference: python/ray/cluster_utils.py:108 (Cluster / add_node :174) —
the cornerstone of distributed testing: N real node daemons + one GCS
as local processes, so scheduling, transfer, and failure logic is
exercised without a real cluster.

Usage::

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address, num_cpus=0)
    ...  # tasks now execute on the worker daemons
    cluster.shutdown()
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field


@dataclass
class NodeHandle:
    """One worker-node daemon process."""

    proc: subprocess.Popen
    resources: dict = field(default_factory=dict)

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None


class Cluster:
    """Head GCS (in-process) + worker-node daemons (OS processes)."""

    def __init__(self, *, initialize_head: bool = True,
                 log_dir: str | None = None,
                 heartbeat_timeout_s: float = 10.0,
                 persist_path: str | None = None):
        from ray_tpu._private.gcs_server import GcsServer

        self._nodes: list[NodeHandle] = []
        self.gcs = None
        self._log_dir = log_dir or f"/tmp/ray_tpu_cluster_{os.getpid()}"
        self._heartbeat_timeout_s = heartbeat_timeout_s
        # Durable head (gcs_persistence): hand a persist_path to arm
        # snapshot+WAL+epoch — restart_head() then exercises the full
        # crash-recovery path in-process (chaos soaks ride this).
        self._persist_path = persist_path
        if initialize_head:
            self.gcs = GcsServer(
                host="127.0.0.1", port=0,
                log_dir=self._log_dir,
                heartbeat_timeout_s=heartbeat_timeout_s,
                persist_path=persist_path)
            self.gcs.start()

    def restart_head(self, graceful: bool = False) -> None:
        """Kill the in-process head and restart it on the SAME port
        from its persisted state (reference: the GCS-restart test
        harnesses). ``graceful=False`` is the crash shape: the RPC
        server dies without a final snapshot — recovery must come from
        the durable snapshot + WAL alone."""
        from ray_tpu._private.gcs_server import GcsServer

        if self.gcs is None:
            raise RuntimeError("cluster has no head")
        port = self.gcs._server.port
        if graceful:
            self.gcs.stop()
        else:
            # Crash: tear down the transport + monitor only. No final
            # snapshot, no WAL close — exactly what SIGKILL leaves.
            self.gcs._shutdown.set()
            self.gcs._server.stop()
        deadline = time.monotonic() + 10
        last_exc = None
        while time.monotonic() < deadline:
            try:
                self.gcs = GcsServer(
                    host="127.0.0.1", port=port,
                    log_dir=self._log_dir,
                    heartbeat_timeout_s=self._heartbeat_timeout_s,
                    persist_path=self._persist_path)
                break
            except OSError as exc:  # port still in TIME_WAIT
                last_exc = exc
                time.sleep(0.2)
        else:
            raise RuntimeError(
                f"head failed to rebind port {port}: {last_exc}")
        self.gcs.start()

    @property
    def address(self) -> str:
        if self.gcs is None:
            raise RuntimeError("cluster has no head")
        return self.gcs.address

    # -- membership ---------------------------------------------------
    def add_node(self, *, num_cpus: float = 2.0,
                 resources: dict | None = None,
                 pool_size: int = 2, env: dict | None = None,
                 heartbeat_period_s: float | None = None) -> NodeHandle:
        """Start a worker-node daemon (executor service + worker pool)
        as a real OS process (reference: cluster_utils.add_node)."""
        from ray_tpu._private.node import daemon_child_env

        node_resources = {"CPU": float(num_cpus)}
        node_resources.update(resources or {})
        extra_kwargs = {}
        if heartbeat_period_s is not None:
            extra_kwargs["heartbeat_period_s"] = heartbeat_period_s
        child_env = daemon_child_env(env)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node", "worker",
             json.dumps({"gcs_address": self.address,
                         "resources": node_resources,
                         "pool_size": pool_size, **extra_kwargs})],
            env=child_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        handle = NodeHandle(proc=proc, resources=node_resources)
        self._nodes.append(handle)
        return handle

    def remove_node(self, node: NodeHandle, *,
                    allow_graceful: bool = True) -> None:
        """Stop a daemon (SIGTERM drains; SIGKILL simulates a crash —
        reference: cluster_utils.remove_node / NodeKillerActor)."""
        if allow_graceful:
            node.proc.terminate()
        else:
            node.proc.kill()
        try:
            node.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            node.proc.kill()
            node.proc.wait(timeout=5)
        if node in self._nodes:
            self._nodes.remove(node)

    def wait_for_nodes(self, count: int | None = None,
                       timeout: float = 30.0) -> bool:
        """Block until ``count`` (default: all added) worker daemons are
        registered with live executor services."""
        from ray_tpu._private.rpc import RpcClient, RpcError

        want = count if count is not None else len(self._nodes)
        client = RpcClient(self.address)
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                try:
                    nodes = client.call("list_nodes")
                except (RpcError, OSError):
                    time.sleep(0.2)
                    continue
                alive = [n for n in nodes
                         if n["alive"] and n.get("executor_address")]
                if len(alive) >= want:
                    return True
                time.sleep(0.2)
            return False
        finally:
            client.close()

    @property
    def worker_nodes(self) -> list[NodeHandle]:
        return list(self._nodes)

    # -- lifecycle ----------------------------------------------------
    def shutdown(self) -> None:
        for node in list(self._nodes):
            try:
                self.remove_node(node)
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
        if self.gcs is not None:
            self.gcs.stop()
            self.gcs = None

    def __enter__(self) -> "Cluster":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
