"""Blockwise (flash) causal attention as a pallas TPU kernel.

The reference framework has no attention kernels at all (SURVEY §5
long-context: absent — it launches torch models); this is a native
capability of the TPU build. Design per the pallas guide
(/opt/skills/guides/pallas_guide.md):

- grid = (batch*heads, L/block_q); each program owns one q tile in VMEM
  and streams k/v tiles from the per-(b,h) VMEM block with online
  softmax (running max/denominator) — O(block) VMEM, no [L, L] scores
  materialized in HBM;
- causal programs stop their k loop at the diagonal (work ∝ L²/2);
- matmuls hit the MXU via jnp.dot with preferred_element_type=f32,
  softmax statistics stay f32 while tiles stay input-dtype;
- backward: custom_vjp whose bwd differentiates a checkpointed
  blockwise lax.scan reference (recompute instead of storing scores —
  activation memory O(L·D), the flash-backward tradeoff) so the op is
  trainable today; a fused bwd kernel can replace it transparently.

On CPU (tests / virtual mesh) the kernel runs in interpret mode
automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU backend only; tests run interpret mode on CPU.
    from jax.experimental.pallas import tpu as pltpu

    _MEMSPACE = pltpu.VMEM
except Exception:  # pragma: no cover - pallas TPU backend unavailable
    pltpu = None
    _MEMSPACE = None

NEG_INF = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                 scale: float, causal: bool, seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, D]
    d = q.shape[-1]

    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    if causal:
        # Only k blocks at or left of the diagonal.
        num_k_blocks = lax.div(qi * block_q, block_k) + pl.cdiv(
            block_q, block_k)
        num_k_blocks = jnp.minimum(num_k_blocks, seq_len // block_k)
    else:
        num_k_blocks = seq_len // block_k

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, v,
                                    preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, d), dtype=jnp.float32)
    _, l_fin, acc = lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l_fin, 1e-30)).astype(o_ref.dtype)


def _fit_block(requested: int, seq_len: int) -> int:
    """Largest divisor of seq_len ≤ requested — the grid and k-loop use
    exact tiling, so a non-dividing block would silently drop tail rows/
    keys. Correctness over tile-shape preference."""
    b = min(requested, seq_len)
    while seq_len % b:
        b -= 1
    return b


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    """q/k/v: [BH, L, D] → o [BH, L, D]."""
    bh, seq_len, d = q.shape
    block_q = _fit_block(block_q, seq_len)
    block_k = _fit_block(block_k, seq_len)
    scale = d ** -0.5
    kernel = functools.partial(
        _attn_kernel, block_q=block_q, block_k=block_k, scale=scale,
        causal=causal, seq_len=seq_len)
    spec_kwargs = {}
    if _MEMSPACE is not None and not interpret:
        spec_kwargs["memory_space"] = _MEMSPACE
    return pl.pallas_call(
        kernel,
        grid=(bh, seq_len // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         **spec_kwargs),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0),
                         **spec_kwargs),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0),
                         **spec_kwargs),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                               **spec_kwargs),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


def _blockwise_reference(q, k, v, causal: bool, block_k: int):
    """Pure-JAX blockwise attention (same online-softmax math); its
    checkpointed vjp is the flash backward."""
    bh, seq_len, d = q.shape
    block_k = _fit_block(block_k, seq_len)
    scale = d ** -0.5
    qf = q.astype(jnp.float32) * scale
    q_pos = jnp.arange(seq_len)[:, None]
    n_blocks = seq_len // block_k
    kb = k.astype(jnp.float32).reshape(bh, n_blocks, block_k, d)
    vb = v.astype(jnp.float32).reshape(bh, n_blocks, block_k, d)

    def step(carry, blk):
        m_prev, l_prev, acc = carry
        kj, vj, j = blk
        s = jnp.einsum("bqd,bkd->bqk", qf, kj)
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bqk,bkd->bqd", p, vj)
        return (m_new, l_new, acc), None

    m0 = jnp.full((bh, seq_len, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((bh, seq_len, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((bh, seq_len, d), dtype=jnp.float32)
    (_, l_fin, acc), _ = lax.scan(
        jax.checkpoint(step, prevent_cse=False),
        (m0, l0, acc0),
        (kb.swapaxes(0, 1), vb.swapaxes(0, 1), jnp.arange(n_blocks)))
    return (acc / jnp.maximum(l_fin, 1e-30)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal, block_q, block_k, interpret)


def _core_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _core_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _blockwise_reference(q, k, v, causal, block_k),
        q, k, v)
    return vjp(g)


_flash_core.defvjp(_core_fwd, _core_bwd)


def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Flash attention over [B, L, H, D] (layout used by models/llama).

    GQA (fewer kv heads than q heads) is handled by repeating kv heads.
    Differentiable (custom vjp). ``interpret=None`` auto-selects
    interpret mode off-TPU.
    """
    b, l, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        reps = h // kvh
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    # [B, L, H, D] -> [B*H, L, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, l, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, l, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, l, d)
    out = _flash_core(qt, kt, vt, causal, block_q, block_k, interpret)
    return out.reshape(b, h, l, d).transpose(0, 2, 1, 3)
