"""Blockwise (flash) causal attention as pallas TPU kernels, fwd + bwd.

The reference framework has no attention kernels at all (SURVEY §5
long-context: absent — it launches torch models); this is a native
capability of the TPU build. Design per the pallas guide
(/opt/skills/guides/pallas_guide.md):

- forward: grid = (batch*heads, L/block_q); each program owns one q tile
  in VMEM and streams k/v tiles from the per-(b,h) VMEM block with
  online softmax (running max/denominator) — O(block) VMEM, no [L, L]
  scores materialized in HBM. Also emits the per-row logsumexp (LSE)
  residual for the backward.
- backward: two fused kernels using the saved LSE (no online softmax
  needed — probabilities are recomputed exactly as exp(s - lse)):
  * dq kernel, grid (batch*heads, L/block_q): for one q tile, loop over
    k tiles at-or-left-of the diagonal accumulating
    dq += (p ∘ (dO·Vᵀ - D)) · K.
  * dk/dv kernel, grid (batch*heads, L/block_k): for one k tile, loop
    over q tiles at-or-below the diagonal accumulating
    dv += pᵀ·dO and dk += (p ∘ (dO·Vᵀ - D))ᵀ · Q.
  D = rowsum(dO ∘ O) is recomputed per q tile from the O residual —
  cheaper than a third pass or an HBM round-trip.
- matmul operands stay bf16 (MXU native) with
  preferred_element_type=f32 accumulation; softmax statistics are f32.
- causal programs stop their k loop at the diagonal (work ∝ L²/2), and
  the dk/dv kernel starts its q loop there.

On CPU (tests / virtual mesh) the kernels run in interpret mode
automatically. ``_blockwise_reference`` remains as the correctness
oracle for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU backend only; tests run interpret mode on CPU.
    from jax.experimental.pallas import tpu as pltpu

    _MEMSPACE = pltpu.VMEM
except Exception:  # pragma: no cover - pallas TPU backend unavailable
    pltpu = None
    _MEMSPACE = None

NEG_INF = -1e30


# ------------------------------------------------------------------ forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
                block_k: int, scale: float, causal: bool, seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0]                                      # [bq, D] bf16
    d = q.shape[-1]

    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    if causal:
        # Only k blocks at or left of the diagonal.
        num_k_blocks = lax.div(qi * block_q, block_k) + pl.cdiv(
            block_q, block_k)
        num_k_blocks = jnp.minimum(num_k_blocks, seq_len // block_k)
    else:
        num_k_blocks = seq_len // block_k

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]   # bf16
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        # bf16 × bf16 on the MXU, f32 accumulation; scale applied to the
        # f32 result (not the bf16 operand) to keep softmax numerics.
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                    preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, d), dtype=jnp.float32)
    m_fin, l_fin, acc = lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l_fin, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, :, 0] = (m_fin + jnp.log(l_safe))[:, 0]


def _fit_block(requested: int, seq_len: int) -> int:
    """Largest divisor of seq_len ≤ requested — the grid and k-loop use
    exact tiling, so a non-dividing block would silently drop tail rows/
    keys. Correctness over tile-shape preference."""
    b = min(requested, seq_len)
    while seq_len % b:
        b -= 1
    return b


def _specs(shapes_and_maps, interpret):
    kwargs = {}
    if _MEMSPACE is not None and not interpret:
        kwargs["memory_space"] = _MEMSPACE
    return [pl.BlockSpec(shape, index_map, **kwargs)
            for shape, index_map in shapes_and_maps]


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    """q/k/v: [BH, L, D] → (o [BH, L, D], lse [BH, L, 1] f32)."""
    bh, seq_len, d = q.shape
    block_q = _fit_block(block_q, seq_len)
    block_k = _fit_block(block_k, seq_len)
    scale = d ** -0.5
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
        causal=causal, seq_len=seq_len)
    in_specs = _specs([
        ((1, block_q, d), lambda b, i: (b, i, 0)),
        ((1, seq_len, d), lambda b, i: (b, 0, 0)),
        ((1, seq_len, d), lambda b, i: (b, 0, 0)),
    ], interpret)
    out_specs = _specs([
        ((1, block_q, d), lambda b, i: (b, i, 0)),
        ((1, block_q, 1), lambda b, i: (b, i, 0)),
    ], interpret)
    return pl.pallas_call(
        kernel,
        grid=(bh, seq_len // block_q),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_len, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ----------------------------------------------------------------- backward


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dq_ref, *,
                   block_q: int, block_k: int, scale: float, causal: bool,
                   seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0]                                       # [bq, D] bf16
    do = do_ref[0]                                     # [bq, D] bf16
    o = o_ref[0]
    lse = lse_ref[0]                                   # [bq, 1] f32
    d = q.shape[-1]

    # D_i = rowsum(dO ∘ O), f32.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)            # [bq, 1]

    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    if causal:
        num_k_blocks = lax.div(qi * block_q, block_k) + pl.cdiv(
            block_q, block_k)
        num_k_blocks = jnp.minimum(num_k_blocks, seq_len // block_k)
    else:
        num_k_blocks = seq_len // block_k

    def body(j, dq_acc):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                           # [bq, bk] f32
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                          # [bq, bk] f32
        return dq_acc + jnp.dot(ds.astype(k.dtype), k,
                                preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, d), dtype=jnp.float32)
    dq = lax.fori_loop(0, num_k_blocks, body, dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dk_ref,
                    dv_ref, *, block_q: int, block_k: int, scale: float,
                    causal: bool, seq_len: int):
    ki = pl.program_id(1)
    k = k_ref[0]                                       # [bk, D] bf16
    v = v_ref[0]
    d = k.shape[-1]

    k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)

    num_q_blocks = seq_len // block_q
    if causal:
        # q blocks strictly left of this k tile never attend to it.
        first_q_block = lax.div(ki * block_k, block_q)
    else:
        first_q_block = 0

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        o = o_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]   # [bq, 1] f32
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                           # [bq, bk] f32
        pt = p.astype(do.dtype).T                      # [bk, bq]
        dv_acc = dv_acc + jnp.dot(pt, do,
                                  preferred_element_type=jnp.float32)
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)        # [bq, 1]
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)        # [bq, bk]
        dk_acc = dk_acc + jnp.dot(ds.T, q,
                                  preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk0 = jnp.zeros((block_k, d), dtype=jnp.float32)
    dv0 = jnp.zeros((block_k, d), dtype=jnp.float32)
    dk, dv = lax.fori_loop(first_q_block, num_q_blocks, body, (dk0, dv0))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal: bool, block_q: int,
               block_k: int, interpret: bool):
    bh, seq_len, d = q.shape
    block_q = _fit_block(block_q, seq_len)
    block_k = _fit_block(block_k, seq_len)
    scale = d ** -0.5
    kw = dict(block_q=block_q, block_k=block_k, scale=scale, causal=causal,
              seq_len=seq_len)

    full = ((1, seq_len, d), lambda b, i: (b, 0, 0))
    full_lse = ((1, seq_len, 1), lambda b, i: (b, 0, 0))
    q_tile = ((1, block_q, d), lambda b, i: (b, i, 0))
    q_lse = ((1, block_q, 1), lambda b, i: (b, i, 0))
    k_tile = ((1, block_k, d), lambda b, i: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kw),
        grid=(bh, seq_len // block_q),
        in_specs=_specs([q_tile, full, full, q_tile, q_lse, q_tile],
                        interpret),
        out_specs=_specs([q_tile], interpret)[0],
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, o, lse, do)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **kw),
        grid=(bh, seq_len // block_k),
        in_specs=_specs([full, k_tile, k_tile, full, full_lse, full],
                        interpret),
        out_specs=_specs([k_tile, k_tile], interpret),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        interpret=interpret,
    )(q, k, v, o, lse, do)
    return dq, dk, dv


# ------------------------------------------------- reference (test oracle)


def _blockwise_reference(q, k, v, causal: bool, block_k: int):
    """Pure-JAX blockwise attention (same online-softmax math); the
    correctness oracle for the kernels in tests."""
    bh, seq_len, d = q.shape
    block_k = _fit_block(block_k, seq_len)
    scale = d ** -0.5
    qf = q.astype(jnp.float32) * scale
    q_pos = jnp.arange(seq_len)[:, None]
    n_blocks = seq_len // block_k
    kb = k.astype(jnp.float32).reshape(bh, n_blocks, block_k, d)
    vb = v.astype(jnp.float32).reshape(bh, n_blocks, block_k, d)

    def step(carry, blk):
        m_prev, l_prev, acc = carry
        kj, vj, j = blk
        s = jnp.einsum("bqd,bkd->bqk", qf, kj)
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bqk,bkd->bqd", p, vj)
        return (m_new, l_new, acc), None

    m0 = jnp.full((bh, seq_len, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((bh, seq_len, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((bh, seq_len, d), dtype=jnp.float32)
    (_, l_fin, acc), _ = lax.scan(
        jax.checkpoint(step, prevent_cse=False),
        (m0, l0, acc0),
        (kb.swapaxes(0, 1), vb.swapaxes(0, 1), jnp.arange(n_blocks)))
    return (acc / jnp.maximum(l_fin, 1e-30)).astype(q.dtype)


# ------------------------------------------------------------- public entry


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o


def _core_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _core_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g.astype(q.dtype), causal,
                      block_q, block_k, interpret)


_flash_core.defvjp(_core_fwd, _core_bwd)


def flash_attention_gspmd(q, k, v, causal: bool = True,
                          block_q: int = 512, block_k: int = 512,
                          interpret: bool | None = None):
    """Flash attention callable from inside a GSPMD-jitted model on a
    multi-device mesh.

    Mosaic kernels cannot be auto-partitioned by GSPMD, so on a mesh
    that actually splits batch/heads the pallas call must be dropped
    into shard_map explicitly: batch stays over (dp, fsdp), heads over
    tp, sequence unsharded (ring attention owns the sp axis). With no
    ambient mesh — or a mesh whose dp/fsdp/tp axes are all singleton —
    this is exactly ``flash_attention``.
    """
    import functools

    from ray_tpu._private import jax_compat

    mesh = jax_compat.ambient_mesh()
    if mesh is None or all(dict(mesh.shape).get(a, 1) == 1
                           for a in ("dp", "fsdp", "tp")):
        return flash_attention(q, k, v, causal, block_q, block_k,
                               interpret)
    from jax.sharding import PartitionSpec as P

    spec = P(("dp", "fsdp"), None, "tp", None)

    @functools.partial(jax_compat.shard_map,
                       in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def inner(q, k, v):
        return flash_attention(q, k, v, causal, block_q, block_k,
                               interpret)

    return inner(q, k, v)


def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512, interpret: bool | None = None):
    """Flash attention over [B, L, H, D] (layout used by models/llama).

    GQA-native: with fewer kv heads than q heads the kernel runs once
    per query-head group over the SAME kv tensors — repeated kv heads
    are never materialized (a ``jnp.repeat`` would burn HBM bandwidth
    and capacity exactly where flash is supposed to save it); kv
    gradients from the groups accumulate through autodiff.
    Differentiable via fused pallas backward kernels. ``interpret=None``
    auto-selects interpret mode off-TPU.
    """
    b, l, h, d = q.shape
    kvh = k.shape[2]
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    kt = k.transpose(0, 2, 1, 3).reshape(b * kvh, l, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * kvh, l, d)
    if kvh == h:
        qt = q.transpose(0, 2, 1, 3).reshape(b * h, l, d)
        out = _flash_core(qt, kt, vt, causal, block_q, block_k, interpret)
        return out.reshape(b, h, l, d).transpose(0, 2, 1, 3)
    reps = h // kvh
    # q head j attends kv head j // reps: regroup q as
    # [reps, B*kvh, L, D] and vmap the kernel over the rep axis with kv
    # UNMAPPED — pallas folds the vmap into the launch grid and every
    # rep reads the same kv blocks, so utilization matches the dense
    # call without the repeated-kv tensor ever existing. kv gradients
    # sum over the rep axis through the batched vjp.
    qg = q.reshape(b, l, kvh, reps, d).transpose(3, 0, 2, 1, 4)
    qg = qg.reshape(reps, b * kvh, l, d)
    out = jax.vmap(
        lambda qq: _flash_core(qq, kt, vt, causal, block_q, block_k,
                               interpret))(qg)
    out = out.reshape(reps, b, kvh, l, d).transpose(1, 3, 2, 0, 4)
    return out.reshape(b, l, h, d)
