"""ray_tpu.ops — pallas TPU kernels for the hot ops.

The reference delegates all device kernels to torch/CUDA; here they are
first-class: blockwise flash attention (flash_attention.py) and fused
elementwise kernels (fused.py). Every op is differentiable (custom
vjp) and falls back to pallas interpret mode off-TPU so the same code
path runs in CPU tests.
"""

from ray_tpu.ops.flash_attention import flash_attention, flash_attention_gspmd
from ray_tpu.ops.fused import rms_norm

__all__ = ["flash_attention", "flash_attention_gspmd", "rms_norm"]
