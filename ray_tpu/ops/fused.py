"""Fused elementwise pallas kernels: RMSNorm (+ residual add).

HBM-bandwidth ops: one pass over the activation instead of the
separate mean/rsqrt/mul HLOs (XLA usually fuses these anyway inside a
jit; the kernel guarantees it at library boundaries and keeps the f32
statistics on-chip). Analytic custom-vjp backward in plain JAX.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _MEMSPACE = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _MEMSPACE = None


def _rmsnorm_kernel(x_ref, scale_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    normed = x * lax.rsqrt(var + eps)
    o_ref[...] = (normed * scale_ref[...].astype(jnp.float32)).astype(
        o_ref.dtype)


def _rmsnorm_fwd_impl(x2d, scale, eps: float, interpret: bool):
    rows, d = x2d.shape
    block_rows = rows
    # Keep a tile under ~2MB of VMEM f32.
    max_rows = max(1, (512 * 1024) // max(d, 1))
    while block_rows > max_rows and block_rows % 2 == 0:
        block_rows //= 2
    spec_kwargs = {}
    if _MEMSPACE is not None and not interpret:
        spec_kwargs["memory_space"] = _MEMSPACE
    return pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0), **spec_kwargs),
            pl.BlockSpec((d,), lambda i: (0,), **spec_kwargs),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0),
                               **spec_kwargs),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(x2d, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rmsnorm_core(x2d, scale, eps, interpret):
    return _rmsnorm_fwd_impl(x2d, scale, eps, interpret)


def _rms_fwd(x2d, scale, eps, interpret):
    return _rmsnorm_fwd_impl(x2d, scale, eps, interpret), (x2d, scale)


def _rms_bwd(eps, interpret, res, g):
    x2d, scale = res
    x = x2d.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    s = scale.astype(jnp.float32)
    d = x.shape[-1]
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = lax.rsqrt(var + eps)
    normed = x * inv
    d_scale = jnp.sum(gf * normed, axis=0)
    # d/dx of x*inv(x): inv * g*s − x * (x·(g*s)) * inv³ / d
    gs = gf * s
    dot = jnp.sum(gs * x, axis=-1, keepdims=True)
    dx = inv * gs - x * dot * inv ** 3 / d
    return dx.astype(x2d.dtype), d_scale.astype(scale.dtype)


_rmsnorm_core.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, scale, eps: float = 1e-5, interpret: bool | None = None):
    """Fused RMSNorm over the last axis. x: [..., D], scale: [D]."""
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    out = _rmsnorm_core(x2d, scale, eps, interpret)
    return out.reshape(shape)
