"""Trial schedulers: FIFO, ASHA, PBT, PB2, and median stopping.

Reference: python/ray/tune/schedulers/async_hyperband.py (ASHA) — rungs
at grace_period * reduction_factor^k; a trial reaching a rung must be in
the top 1/reduction_factor of results seen at that rung or it stops.
python/ray/tune/schedulers/pbt.py (PBT) — at each perturbation interval,
bottom-quantile trials *exploit* a top-quantile trial (copy its config +
checkpoint) and *explore* (mutate hyperparameters), continuing training
from the copied checkpoint. python/ray/tune/schedulers/pb2.py (PB2) —
PBT whose explore step is model-based: a time-aware Gaussian process
over (t, hyperparams) -> reward change selects new configs by UCB
instead of random perturbation (Parker-Holder et al., NeurIPS 2020).
"""

from __future__ import annotations

import random
from collections import defaultdict
from dataclasses import dataclass, field


CONTINUE = "CONTINUE"
STOP = "STOP"
# PBT: stop this trial and relaunch it with (new_config, checkpoint)
# from Scheduler.exploit(trial_id).
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    def on_result(self, trial_id: str, metrics: dict) -> str:
        return CONTINUE


@dataclass
class ASHAScheduler:
    metric: str = "loss"
    mode: str = "min"  # "min" or "max"
    grace_period: int = 1
    reduction_factor: int = 4
    max_t: int = 100
    time_attr: str = "training_iteration"
    _rungs: dict[int, list[float]] = field(default_factory=lambda: defaultdict(list))
    _recorded: dict[str, set] = field(default_factory=lambda: defaultdict(set))

    def __post_init__(self):
        if self.mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {self.mode}")

    def _rung_levels(self) -> list[int]:
        levels = []
        t = self.grace_period
        while t < self.max_t:
            levels.append(t)
            t *= self.reduction_factor
        return levels

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        score = -float(value) if self.mode == "min" else float(value)
        decision = CONTINUE
        # Milestone semantics (>=): trials reporting on a stride that skips
        # an exact rung value still get evaluated at the first report at or
        # past each rung, once per trial per rung.
        seen = self._recorded[trial_id]
        for level in self._rung_levels():
            if t >= level and level not in seen:
                seen.add(level)
                rung = self._rungs[level]
                rung.append(score)
                if len(rung) >= self.reduction_factor:
                    rung_sorted = sorted(rung, reverse=True)
                    cutoff = rung_sorted[
                        max(0, len(rung) // self.reduction_factor - 1)]
                    if score < cutoff:
                        decision = STOP
        if t >= self.max_t:
            decision = STOP
        return decision


class PopulationBasedTraining:
    """PBT (reference: python/ray/tune/schedulers/pbt.py).

    The controller feeds trial state via ``on_trial_state(trial_id,
    config, checkpoint)`` on every checkpointed report. ``on_result``
    returns EXPLOIT for a bottom-quantile trial at a perturbation
    boundary; the controller then calls ``exploit(trial_id)`` for the
    (mutated_config, source_checkpoint) to relaunch it with.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 perturbation_factors: tuple = (0.8, 1.2),
                 resample_probability: float = 0.25,
                 time_attr: str = "training_iteration",
                 seed: int | None = None):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode}")
        if not hyperparam_mutations:
            raise ValueError("PBT requires hyperparam_mutations")
        self.metric = metric
        self.mode = mode
        self.perturbation_interval = perturbation_interval
        self.hyperparam_mutations = hyperparam_mutations
        self.quantile_fraction = quantile_fraction
        self.perturbation_factors = perturbation_factors
        self.resample_probability = resample_probability
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._scores: dict[str, float] = {}
        self._configs: dict[str, dict] = {}
        self._checkpoints: dict[str, object] = {}
        self._last_perturb: dict[str, int] = {}
        self._exploit_sources: dict[str, str] = {}
        self.num_perturbations = 0

    # ---------------------------------------------------------- state feed

    def on_trial_state(self, trial_id: str, config: dict,
                       checkpoint) -> None:
        self._configs[trial_id] = dict(config)
        if checkpoint is not None:
            self._checkpoints[trial_id] = checkpoint

    # -------------------------------------------------------------- decide

    def _score(self, value: float) -> float:
        return -value if self.mode == "min" else value

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        self._scores[trial_id] = self._score(float(value))
        last = self._last_perturb.get(trial_id, 0)
        if t - last < self.perturbation_interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        ranked = sorted(self._scores, key=self._scores.get)  # worst first
        if len(ranked) < 2:
            return CONTINUE
        n_quantile = max(1, int(len(ranked) * self.quantile_fraction))
        bottom = set(ranked[:n_quantile])
        top = [tid for tid in ranked[-n_quantile:]
               if tid in self._checkpoints and tid != trial_id]
        if trial_id in bottom and top:
            self._exploit_sources[trial_id] = self._rng.choice(top)
            return EXPLOIT
        return CONTINUE

    # ------------------------------------------------------------- exploit

    def exploit(self, trial_id: str):
        """(mutated_config, source_checkpoint) for the stopped trial."""
        source = self._exploit_sources.pop(trial_id, None)
        if source is None:
            raise ValueError(
                f"exploit({trial_id!r}) without a preceding EXPLOIT "
                f"decision for that trial")
        new_config = self._explore(dict(self._configs.get(source, {})))
        self._configs[trial_id] = new_config
        self.num_perturbations += 1
        return new_config, self._checkpoints.get(source)

    def _explore(self, config: dict) -> dict:
        """Mutate each listed hyperparameter (reference: pbt.py explore)."""
        for key, space in self.hyperparam_mutations.items():
            resample = self._rng.random() < self.resample_probability
            current = config.get(key)
            if callable(space):
                config[key] = space()
            elif isinstance(space, (list, tuple)):
                # Stay INSIDE the listed space: shift to an adjacent
                # index (reference pbt.py explore), never multiply —
                # 64 * 0.8 = 51.2 is not a legal batch size.
                values = list(space)
                if resample or current not in values:
                    config[key] = self._rng.choice(values)
                else:
                    idx = values.index(current)
                    shift = self._rng.choice((-1, 1))
                    config[key] = values[min(len(values) - 1,
                                             max(0, idx + shift))]
            elif isinstance(current, (int, float)):
                config[key] = current * self._rng.choice(
                    self.perturbation_factors)
        return config


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference:
    python/ray/tune/schedulers/pb2.py; the reference wraps GPy — here
    the GP is ~40 lines of numpy, same RBF-kernel UCB acquisition).

    Instead of PBT's random perturbation, explore fits a Gaussian
    process on observations ((t, hyperparams) -> score improvement)
    and proposes the config maximizing UCB = mu + kappa * sigma within
    ``hyperparam_bounds`` — sample-efficient for small populations,
    where random perturbation thrashes.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: dict | None = None,
                 quantile_fraction: float = 0.25,
                 time_attr: str = "training_iteration",
                 kappa: float = 2.0, lengthscale: float = 0.3,
                 noise: float = 1e-3, n_candidates: int = 128,
                 max_observations: int = 256,
                 seed: int | None = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds")
        for key, bounds in hyperparam_bounds.items():
            if len(bounds) != 2 or not bounds[0] < bounds[1]:
                raise ValueError(
                    f"hyperparam_bounds[{key!r}] must be (low, high); "
                    f"got {bounds}")
        # The base class only reads hyperparam_mutations in _explore,
        # which PB2 overrides — pass bounds to satisfy the constructor.
        super().__init__(
            metric=metric, mode=mode,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations=dict(hyperparam_bounds),
            quantile_fraction=quantile_fraction, time_attr=time_attr,
            seed=seed)
        self.hyperparam_bounds = {
            k: (float(lo), float(hi))
            for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = kappa
        self.lengthscale = lengthscale
        self.noise = noise
        self.n_candidates = n_candidates
        self.max_observations = max_observations
        self._prev_score: dict[str, float] = {}
        # GP dataset: rows of [t_norm, x_norm...] -> score delta.
        self._obs_x: list[list[float]] = []
        self._obs_y: list[float] = []
        self._t_max = 1.0

    # -- observation feed ---------------------------------------------
    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is not None and value is not None:
            score = self._score(float(value))
            prev = self._prev_score.get(trial_id)
            config = self._configs.get(trial_id)
            if prev is not None and config is not None:
                self._t_max = max(self._t_max, float(t))
                self._obs_x.append(
                    [float(t)] + self._vec(config))
                self._obs_y.append(score - prev)
                if len(self._obs_y) > self.max_observations:
                    del self._obs_x[0]
                    del self._obs_y[0]
            self._prev_score[trial_id] = score
        return super().on_result(trial_id, metrics)

    def exploit(self, trial_id: str):
        # The exploiting trial jumps to the source's checkpointed score;
        # its next delta would otherwise record that jump as if the NEW
        # hyperparams caused it, poisoning the GP with a huge outlier.
        self._prev_score.pop(trial_id, None)
        return super().exploit(trial_id)

    # -- GP-UCB explore ------------------------------------------------
    def _vec(self, config: dict) -> list[float]:
        out = []
        for key, (lo, hi) in self.hyperparam_bounds.items():
            v = float(config.get(key, (lo + hi) / 2))
            out.append((v - lo) / (hi - lo))
        return out

    def _explore(self, config: dict) -> dict:
        import numpy as np

        keys = list(self.hyperparam_bounds)
        cands = np.array([
            [self._rng.random() for _ in keys]
            for _ in range(self.n_candidates)])          # [C, d] in [0,1]
        if len(self._obs_y) >= 4:
            X = np.asarray(self._obs_x, dtype=float)
            X[:, 0] /= self._t_max                       # normalize t
            y = np.asarray(self._obs_y, dtype=float)
            y_std = y.std() or 1.0
            y_n = (y - y.mean()) / y_std
            t_now = np.full((len(cands), 1),
                            min(1.0, (max(x[0] for x in self._obs_x)
                                      / self._t_max)))
            C = np.concatenate([t_now, cands], axis=1)   # [C, d+1]

            def rbf(a, b):
                d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
                return np.exp(-0.5 * d2 / self.lengthscale ** 2)

            K = rbf(X, X) + self.noise * np.eye(len(X))
            Ks = rbf(C, X)                               # [C, N]
            alpha = np.linalg.solve(K, y_n)
            mu = Ks @ alpha
            v = np.linalg.solve(K, Ks.T)
            var = np.maximum(1.0 - np.einsum("cn,nc->c", Ks, v), 1e-12)
            best = int(np.argmax(mu + self.kappa * np.sqrt(var)))
        else:
            best = int(self._rng.random() * len(cands)) % len(cands)
        chosen = cands[best]
        for key, unit in zip(keys, chosen):
            lo, hi = self.hyperparam_bounds[key]
            config[key] = lo + float(unit) * (hi - lo)
        return config


class MedianStoppingRule:
    """Stop trials whose best result falls below the median of running
    averages at the same time step (reference:
    python/ray/tune/schedulers/median_stopping_rule.py — the Vizier
    median stopping rule).

    A trial is evaluated after ``grace_period`` steps, against the
    median of the OTHER trials' running-average scores; fewer than
    ``min_samples_required`` completed/running peers means CONTINUE.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode}")
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples_required = min_samples_required
        self.time_attr = time_attr
        self._history: dict[str, list] = {}  # tid -> [(t, score)]
        self._best: dict[str, float] = {}
        self.num_stopped = 0

    def _score(self, value: float) -> float:
        return -value if self.mode == "min" else value

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        score = self._score(float(value))
        self._history.setdefault(trial_id, []).append((t, score))
        self._best[trial_id] = max(
            self._best.get(trial_id, -float("inf")), score)
        if t < self.grace_period:
            return CONTINUE
        # Peer averages ALIGNED to this trial's step: only results up
        # to t count, else a slow-but-equal trial compares against
        # peers' later (better) scores and dies unfairly (the Vizier
        # rule restricts to the same step for exactly this reason).
        peers = []
        for tid, history in self._history.items():
            if tid == trial_id:
                continue
            upto = [s for ts, s in history if ts <= t]
            if upto:
                peers.append(sum(upto) / len(upto))
        if len(peers) < self.min_samples_required:
            return CONTINUE
        peers.sort()
        n = len(peers)
        median = (peers[n // 2] if n % 2
                  else (peers[n // 2 - 1] + peers[n // 2]) / 2.0)
        if self._best[trial_id] < median:
            self.num_stopped += 1
            return STOP
        return CONTINUE
