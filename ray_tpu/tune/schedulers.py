"""Trial schedulers: FIFO and ASHA.

Reference: python/ray/tune/schedulers/async_hyperband.py (ASHA) — rungs
at grace_period * reduction_factor^k; a trial reaching a rung must be in
the top 1/reduction_factor of results seen at that rung or it stops.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field


CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, metrics: dict) -> str:
        return CONTINUE


@dataclass
class ASHAScheduler:
    metric: str = "loss"
    mode: str = "min"  # "min" or "max"
    grace_period: int = 1
    reduction_factor: int = 4
    max_t: int = 100
    time_attr: str = "training_iteration"
    _rungs: dict[int, list[float]] = field(default_factory=lambda: defaultdict(list))
    _recorded: dict[str, set] = field(default_factory=lambda: defaultdict(set))

    def __post_init__(self):
        if self.mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {self.mode}")

    def _rung_levels(self) -> list[int]:
        levels = []
        t = self.grace_period
        while t < self.max_t:
            levels.append(t)
            t *= self.reduction_factor
        return levels

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        score = -float(value) if self.mode == "min" else float(value)
        decision = CONTINUE
        # Milestone semantics (>=): trials reporting on a stride that skips
        # an exact rung value still get evaluated at the first report at or
        # past each rung, once per trial per rung.
        seen = self._recorded[trial_id]
        for level in self._rung_levels():
            if t >= level and level not in seen:
                seen.add(level)
                rung = self._rungs[level]
                rung.append(score)
                if len(rung) >= self.reduction_factor:
                    rung_sorted = sorted(rung, reverse=True)
                    cutoff = rung_sorted[
                        max(0, len(rung) // self.reduction_factor - 1)]
                    if score < cutoff:
                        decision = STOP
        if t >= self.max_t:
            decision = STOP
        return decision
