"""Search spaces and variant generation.

Reference: python/ray/tune/search/ — basic_variant (grid/random),
sample.py domains (choice/uniform/loguniform/randint), and
ConcurrencyLimiter semantics (max_concurrent in TuneConfig).
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class GridSearch:
    values: list


@dataclass
class Choice:
    values: list

    def sample(self, rng: random.Random):
        return rng.choice(self.values)


@dataclass
class Uniform:
    low: float
    high: float

    def sample(self, rng: random.Random):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform:
    low: float
    high: float

    def sample(self, rng: random.Random):
        import math

        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class RandInt:
    low: int
    high: int

    def sample(self, rng: random.Random):
        return rng.randrange(self.low, self.high)


@dataclass
class Func:
    fn: Callable[[dict], Any]

    def sample(self, rng: random.Random):
        return self.fn(None)


def grid_search(values: list) -> GridSearch:
    return GridSearch(list(values))


def choice(values: list) -> Choice:
    return Choice(list(values))


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def sample_from(fn: Callable) -> Func:
    return Func(fn)


def generate_variants(param_space: dict, num_samples: int = 1,
                      seed: int | None = None) -> list[dict]:
    """Grid axes are expanded exhaustively; stochastic domains are sampled
    ``num_samples`` times per grid point (reference: basic_variant.py)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grid_values = [param_space[k].values for k in grid_keys]
    variants = []
    grid_points = list(itertools.product(*grid_values)) if grid_keys else [()]
    for point in grid_points:
        for _ in range(num_samples):
            config = {}
            for key, value in param_space.items():
                if isinstance(value, GridSearch):
                    config[key] = point[grid_keys.index(key)]
                elif hasattr(value, "sample"):
                    config[key] = value.sample(rng)
                else:
                    config[key] = value
            variants.append(config)
    return variants


# ----------------------------------------------------------- searcher plugin


class Searcher:
    """Pluggable search algorithm (reference: tune/search/searcher.py
    Searcher: suggest / on_trial_result / on_trial_complete). Set via
    ``TuneConfig(search_alg=...)``; the Tuner then asks the searcher for
    each trial's config instead of pre-generating variants."""

    def __init__(self, metric: str | None = None, mode: str | None = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: str, mode: str,
                              param_space: dict) -> None:
        """Called once by the Tuner before the first suggest."""
        self.metric = self.metric or metric
        self.mode = self.mode or mode

    def suggest(self, trial_id: str) -> dict | None:
        """Next config to evaluate; None = nothing more to suggest."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        """Intermediate result (optional hook)."""

    def on_trial_complete(self, trial_id: str, result: dict | None,
                          error: bool = False) -> None:
        """Terminal result for a trial this searcher suggested."""


class BasicVariantSearcher(Searcher):
    """generate_variants wrapped in the Searcher interface — what the
    Tuner uses when no search_alg is given."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: int | None = None):
        super().__init__()
        self._variants = generate_variants(param_space, num_samples, seed)
        self._next = 0

    def suggest(self, trial_id: str) -> dict | None:
        if self._next >= len(self._variants):
            return None
        config = self._variants[self._next]
        self._next += 1
        return config


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator (Bergstra et al. 2011) —
    the built-in analogue of the reference's hyperopt integration
    (tune/search/hyperopt/). Supports Choice / Uniform / LogUniform /
    RandInt domains; GridSearch axes are rejected (grids belong to the
    basic variant generator).

    Per dimension, observed configs split into the top ``gamma``
    fraction (good) and the rest (bad); candidates are drawn from a
    kernel density over the good values and scored by the density ratio
    l_good / l_bad — the classic TPE acquisition.
    """

    def __init__(self, metric: str | None = None, mode: str | None = None,
                 n_initial_points: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int | None = None):
        super().__init__(metric=metric, mode=mode)
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._space: dict = {}
        self._suggested: dict[str, dict] = {}
        self._observed: list[tuple[dict, float]] = []

    def set_search_properties(self, metric: str, mode: str,
                              param_space: dict) -> None:
        super().set_search_properties(metric, mode, param_space)
        for key, dom in param_space.items():
            if isinstance(dom, GridSearch):
                raise ValueError(
                    "TPESearcher does not accept grid_search axes; use "
                    "choice() or the default variant generator")
        self._space = dict(param_space)

    # -- observation --------------------------------------------------
    def on_trial_complete(self, trial_id: str, result: dict | None,
                          error: bool = False) -> None:
        config = self._suggested.pop(trial_id, None)
        if config is None or error or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "min" else -float(value)
        self._observed.append((config, score))

    # -- suggestion ---------------------------------------------------
    def _random_config(self) -> dict:
        config = {}
        for key, dom in self._space.items():
            config[key] = dom.sample(self._rng) if hasattr(dom, "sample") \
                else dom
        return config

    @staticmethod
    def _kde_logpdf(values: list[float], x: float, bandwidth: float) -> float:
        import math

        if not values:
            return 0.0
        total = 0.0
        for v in values:
            total += math.exp(-0.5 * ((x - v) / bandwidth) ** 2)
        return math.log(max(total / (len(values) * bandwidth), 1e-12))

    def _dim_score(self, dom, good: list, bad: list, x) -> float:
        import math

        if not isinstance(dom, (Choice, Uniform, LogUniform, RandInt)):
            return 0.0  # Func/sample_from etc: no density model
        if isinstance(dom, Choice):
            smoothing = 1.0
            n_opts = max(len(dom.values), 1)
            pg = (good.count(x) + smoothing) / (len(good) + smoothing * n_opts)
            pb = (bad.count(x) + smoothing) / (len(bad) + smoothing * n_opts)
            return math.log(pg) - math.log(pb)
        to_float = math.log if isinstance(dom, LogUniform) else float
        lo = to_float(dom.low)
        hi = to_float(dom.high)
        bandwidth = max((hi - lo) / 5.0, 1e-9)
        xg = [to_float(v) for v in good]
        xb = [to_float(v) for v in bad]
        xv = to_float(x)
        return (self._kde_logpdf(xg, xv, bandwidth)
                - self._kde_logpdf(xb, xv, bandwidth))

    def suggest(self, trial_id: str) -> dict | None:
        if len(self._observed) < self.n_initial:
            config = self._random_config()
        else:
            ranked = sorted(self._observed, key=lambda cv: cv[1])
            n_good = max(1, int(self.gamma * len(ranked)))
            good_cfgs = [c for c, _ in ranked[:n_good]]
            bad_cfgs = [c for c, _ in ranked[n_good:]] or good_cfgs
            best, best_score = None, -float("inf")
            for _ in range(self.n_candidates):
                cand = {}
                for key, dom in self._space.items():
                    if not hasattr(dom, "sample"):
                        cand[key] = dom
                        continue
                    # Sample near a good observation (jittered), falling
                    # back to the prior.
                    if isinstance(dom, Choice) or self._rng.random() < 0.25:
                        cand[key] = dom.sample(self._rng)
                    else:
                        base = self._rng.choice(good_cfgs)[key]
                        cand[key] = self._jitter(dom, base)
                score = sum(
                    self._dim_score(dom, [g[k] for g in good_cfgs],
                                    [b[k] for b in bad_cfgs], cand[k])
                    for k, dom in self._space.items()
                    if hasattr(dom, "sample"))
                if score > best_score:
                    best, best_score = cand, score
            config = best or self._random_config()
        self._suggested[trial_id] = config
        return config

    def _jitter(self, dom, base):
        import math

        if isinstance(dom, LogUniform):
            lo, hi = math.log(dom.low), math.log(dom.high)
            x = math.log(base) + self._rng.gauss(0, (hi - lo) / 5.0)
            return math.exp(min(max(x, lo), hi))
        if isinstance(dom, Uniform):
            x = base + self._rng.gauss(0, (dom.high - dom.low) / 5.0)
            return min(max(x, dom.low), dom.high)
        if isinstance(dom, RandInt):
            x = base + int(round(self._rng.gauss(0, max(
                (dom.high - dom.low) / 5.0, 1.0))))
            return min(max(x, dom.low), dom.high - 1)
        return dom.sample(self._rng)


class TrialParams:
    """The ``trial`` object handed to a define-by-run function
    (reference: tune/search/optuna — OptunaSearch's define-by-run mode;
    API mirrors optuna.Trial.suggest_*)."""

    def __init__(self, sampler):
        self._sampler = sampler
        self.params: dict = {}

    def _suggest(self, name: str, dom):
        if name in self.params:
            return self.params[name]
        value = self._sampler(name, dom)
        self.params[name] = value
        return value

    def suggest_float(self, name: str, low: float, high: float,
                      log: bool = False):
        return self._suggest(
            name, LogUniform(low, high) if log else Uniform(low, high))

    def suggest_int(self, name: str, low: int, high: int):
        # Inclusive bounds like optuna; RandInt is exclusive-high.
        return self._suggest(name, RandInt(low, high + 1))

    def suggest_categorical(self, name: str, choices):
        return self._suggest(name, Choice(list(choices)))


class DefineByRunSearcher(Searcher):
    """Optuna-style define-by-run search on the Searcher plugin API
    (reference: tune/search/optuna/optuna_search.py's ``space`` as a
    callable). The search space is DISCOVERED by executing the user's
    ``define(trial)`` function; each parameter is sampled by a
    per-parameter TPE over the completed trials where it appeared, so
    conditional parameters (suggested only down some branch) are
    handled naturally — absent parameters simply have no observations.

    ``define`` may return a dict of extra constants merged into the
    trial config, or None (the suggested params ARE the config).
    """

    def __init__(self, define, metric: str | None = None,
                 mode: str | None = None, n_initial_points: int = 8,
                 gamma: float = 0.25, n_candidates: int = 16,
                 seed: int | None = None):
        super().__init__(metric=metric, mode=mode)
        self._define = define
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        # Density/jitter machinery shared with the space-dict TPE.
        self._tpe = TPESearcher(seed=seed)
        self._suggested: dict[str, dict] = {}
        self._observed: list[tuple[dict, float]] = []

    def set_search_properties(self, metric: str, mode: str,
                              param_space: dict) -> None:
        # The space comes from the define fn; a param_space dict (other
        # than {}) would silently be ignored — refuse instead.
        if param_space:
            raise ValueError(
                "DefineByRunSearcher discovers the space from its "
                "define() function; pass param_space={} to the Tuner")
        self.metric = self.metric or metric
        self.mode = self.mode or mode

    def _sample_param(self, name: str, dom):
        relevant = [(cfg[name], score) for cfg, score in self._observed
                    if name in cfg]
        if len(relevant) < self.n_initial or not hasattr(dom, "sample"):
            return dom.sample(self._rng)
        ranked = sorted(relevant, key=lambda vs: vs[1])
        n_good = max(1, int(self.gamma * len(ranked)))
        good = [v for v, _ in ranked[:n_good]]
        bad = [v for v, _ in ranked[n_good:]] or good
        best, best_score = None, -float("inf")
        for _ in range(self.n_candidates):
            if isinstance(dom, Choice) or self._rng.random() < 0.25:
                cand = dom.sample(self._rng)
            else:
                cand = self._tpe._jitter(dom, self._rng.choice(good))
            score = self._tpe._dim_score(dom, good, bad, cand)
            if score > best_score:
                best, best_score = cand, score
        return best if best is not None else dom.sample(self._rng)

    def suggest(self, trial_id: str) -> dict | None:
        trial = TrialParams(self._sample_param)
        extras = self._define(trial)
        config = dict(trial.params)
        if isinstance(extras, dict):
            config.update(extras)
        self._suggested[trial_id] = dict(trial.params)
        return config

    def on_trial_complete(self, trial_id: str, result: dict | None,
                          error: bool = False) -> None:
        params = self._suggested.pop(trial_id, None)
        if params is None or error or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "min" else -float(value)
        self._observed.append((params, score))
