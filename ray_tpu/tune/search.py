"""Search spaces and variant generation.

Reference: python/ray/tune/search/ — basic_variant (grid/random),
sample.py domains (choice/uniform/loguniform/randint), and
ConcurrencyLimiter semantics (max_concurrent in TuneConfig).
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class GridSearch:
    values: list


@dataclass
class Choice:
    values: list

    def sample(self, rng: random.Random):
        return rng.choice(self.values)


@dataclass
class Uniform:
    low: float
    high: float

    def sample(self, rng: random.Random):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform:
    low: float
    high: float

    def sample(self, rng: random.Random):
        import math

        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class RandInt:
    low: int
    high: int

    def sample(self, rng: random.Random):
        return rng.randrange(self.low, self.high)


@dataclass
class Func:
    fn: Callable[[dict], Any]

    def sample(self, rng: random.Random):
        return self.fn(None)


def grid_search(values: list) -> GridSearch:
    return GridSearch(list(values))


def choice(values: list) -> Choice:
    return Choice(list(values))


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def sample_from(fn: Callable) -> Func:
    return Func(fn)


def generate_variants(param_space: dict, num_samples: int = 1,
                      seed: int | None = None) -> list[dict]:
    """Grid axes are expanded exhaustively; stochastic domains are sampled
    ``num_samples`` times per grid point (reference: basic_variant.py)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grid_values = [param_space[k].values for k in grid_keys]
    variants = []
    grid_points = list(itertools.product(*grid_values)) if grid_keys else [()]
    for point in grid_points:
        for _ in range(num_samples):
            config = {}
            for key, value in param_space.items():
                if isinstance(value, GridSearch):
                    config[key] = point[grid_keys.index(key)]
                elif hasattr(value, "sample"):
                    config[key] = value.sample(rng)
                else:
                    config[key] = value
            variants.append(config)
    return variants
