"""ray_tpu.tune — hyperparameter optimization (reference: python/ray/tune)."""

from ray_tpu.train.session import get_checkpoint
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    BasicVariantSearcher,
    DefineByRunSearcher,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.tuner import (
    ResultGrid,
    TrialResult,
    TuneConfig,
    Tuner,
    run,
)


def report(metrics: dict, checkpoint=None) -> None:
    """In-trial reporting (reference: ray.tune.report / session.report)."""
    from ray_tpu.train.session import report as _report

    _report(metrics, checkpoint)


class Trainable:
    """Class trainable protocol (reference: tune/trainable/trainable.py:61).

    Subclasses override setup(config), step() -> dict, and optionally
    save_checkpoint/load_checkpoint/cleanup.
    """

    def __init__(self, config: dict | None = None):
        self.config = config or {}

    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str):
        return None

    def load_checkpoint(self, checkpoint) -> None:
        pass

    def cleanup(self) -> None:
        pass


__all__ = [
    "ASHAScheduler",
    "FIFOScheduler",
    "ResultGrid",
    "Trainable",
    "TrialResult",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "BasicVariantSearcher",
    "Searcher",
    "TPESearcher",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "DefineByRunSearcher",
    "run",
    "sample_from",
    "uniform",
]
