"""Tuner + trial controller.

Reference: python/ray/tune/tuner.py:54/:354 (Tuner.fit) and
execution/tune_controller.py:72/:718 (TuneController event loop managing
trials as actors). Trials here run as tasks on the ray_tpu runtime;
reports stream through a shared queue; the scheduler (ASHA) can stop
trials at rung boundaries via per-trial stop events.

Trainable forms supported (reference: tune/trainable/trainable.py):
- function trainables ``fn(config)`` using ``ray_tpu.tune.report``;
- class Trainables with setup/step/save/restore;
- ray_tpu.train trainers via ``TunableTrainer`` (BaseTrainer.fit wraps a
  trainer in a 1-trial tune run in the reference — here the layering is
  inverted but equivalent: a trainer is just another trainable).
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import (
    StopTraining,
    TrainContext,
    _SessionState,
    _TrainSession,
)
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants


@dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 0  # 0 = unlimited
    scheduler: Any = None
    seed: int | None = None
    max_iterations: int = 0  # 0 = until trainable returns
    # Pluggable search algorithm (Searcher subclass, e.g. TPESearcher);
    # None = exhaustive/random variant generation from param_space.
    search_alg: Any = None
    # Wall-clock budget for the whole run; None = unlimited. On expiry,
    # running trials are stopped and marked with a TimeoutError.
    time_budget_s: float | None = None


@dataclass
class TrialResult:
    trial_id: str
    config: dict
    metrics: dict = field(default_factory=dict)
    history: list = field(default_factory=list)
    error: BaseException | None = None
    checkpoint: Checkpoint | None = None

    @property
    def last_result(self) -> dict:
        return self.metrics


class ResultGrid:
    """Reference: ray.tune.ResultGrid."""

    def __init__(self, results: list[TrialResult], metric: str, mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, idx):
        return self._results[idx]

    @property
    def errors(self) -> list[BaseException]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        valid = [r for r in self._results
                 if r.error is None and metric in r.metrics]
        if not valid:
            raise ValueError("No successful trial reported metric "
                             f"{metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return min(valid, key=key) if mode == "min" else max(valid, key=key)

    def get_dataframe(self):
        rows = [{"trial_id": r.trial_id, **r.config, **r.metrics}
                for r in self._results]
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except Exception:
            return rows


def _run_trial_fn(trainable: Callable, config: dict, trial_id: str,
                  results_queue, stop_event,
                  resume_checkpoint: Checkpoint | None = None) -> Any:
    """Execute one trial inside a task; session routes tune.report."""
    from ray_tpu.train.session import run_with_session

    state = _SessionState(
        context=TrainContext(trial_name=trial_id),
        results_queue=_TaggedQueue(results_queue, trial_id, stop_event),
        stop_event=stop_event,
        resume_checkpoint=resume_checkpoint,
    )

    def emit(msg: dict):
        if msg.get("error") is None and isinstance(msg.get("result"), dict):
            # A trainable may return its final metrics instead of reporting.
            results_queue.put({"trial_id": trial_id, "done": False,
                               "metrics": msg["result"], "checkpoint": None,
                               "iteration": state.iteration + 1})
        results_queue.put({"trial_id": trial_id, "done": True,
                           "error": msg.get("error")})

    try:
        return run_with_session(trainable, config, state, emit)
    except BaseException:  # noqa: BLE001 — recorded via emit; don't fail task
        return None


class _TaggedQueue:
    """Adapts the train-session queue protocol to tagged tune messages.

    Each report blocks until the controller has applied the scheduler
    decision, so early-stopping (ASHA) takes effect on the very next
    report rather than racing the trial loop.
    """

    def __init__(self, inner, trial_id: str, stop_event=None):
        self._inner = inner
        self._trial_id = trial_id
        self._stop_event = stop_event

    def put(self, msg: dict):
        ack = threading.Event()
        self._inner.put({
            "trial_id": self._trial_id,
            "done": msg.get("done", False),
            "metrics": msg.get("metrics", {}),
            "checkpoint": msg.get("checkpoint"),
            "iteration": msg.get("iteration", 0),
            "error": msg.get("error"),
            "ack": ack,
        })
        # Wake promptly on stop: after time_budget_s expiry the controller
        # stops reading the queue, so a report racing the final drain would
        # otherwise block here for the full timeout.
        deadline = time.monotonic() + 60.0
        while not ack.is_set() and time.monotonic() < deadline:
            if self._stop_event is not None and self._stop_event.is_set():
                break
            ack.wait(timeout=0.1)


def _class_trainable_loop(cls: type, max_iterations: int) -> Callable:
    """Adapt a class Trainable to the function protocol."""

    def fn(config: dict):
        from ray_tpu.tune import report

        instance = cls(config) if _takes_config(cls) else cls()
        if hasattr(instance, "setup"):
            instance.setup(config)
        i = 0
        try:
            while True:
                i += 1
                metrics = instance.step()
                metrics.setdefault("training_iteration", i)
                report(metrics)
                if metrics.get("done") or (max_iterations and i >= max_iterations):
                    break
        finally:
            if hasattr(instance, "cleanup"):
                instance.cleanup()

    return fn


def _takes_config(cls: type) -> bool:
    import inspect

    try:
        sig = inspect.signature(cls.__init__)
        return len(sig.parameters) > 1
    except (TypeError, ValueError):
        return False


class Tuner:
    """Reference: ray.tune.Tuner (tuner.py:54). ``Tuner.restore`` resumes
    a previous run from its persisted experiment state (reference:
    Tuner.restore + tune/execution experiment checkpointing)."""

    def __init__(self, trainable: Callable | type, *,
                 param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config=None,
                 _restored_trials: list | None = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config
        self._restored_trials = _restored_trials

    # ------------------------------------------------------ experiment dir

    def _experiment_dir(self) -> str | None:
        run_cfg = self.run_config
        if run_cfg is None or not getattr(run_cfg, "storage_path", None):
            return None
        # Never mutate the caller's RunConfig: a shared config across two
        # Tuners must not make them share (and clobber) one directory.
        if getattr(self, "_exp_name", None) is None:
            self._exp_name = run_cfg.name or \
                f"tune_{int(time.time())}_{uuid.uuid4().hex[:6]}"
        return f"{run_cfg.storage_path}/{self._exp_name}"

    @staticmethod
    def _save_state(exp_dir: str, trials: dict, done: set) -> None:
        """Persist resumable state (reference: the tuner.pkl +
        experiment-state files under the experiment dir)."""
        import os
        import pickle

        state = [
            {
                "trial_id": t.trial_id,
                "config": t.config,
                "status": ("DONE" if t.trial_id in done and t.error is None
                           else "ERROR" if t.trial_id in done else "PENDING"),
                "metrics": t.metrics,
                "history": t.history,
                "checkpoint_path": (t.checkpoint.path
                                    if t.checkpoint is not None else None),
            }
            for t in trials.values()
        ]
        os.makedirs(exp_dir, exist_ok=True)
        tmp = f"{exp_dir}/experiment_state.pkl.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, f"{exp_dir}/experiment_state.pkl")

    @classmethod
    def restore(cls, path: str, trainable: Callable | type, *,
                tune_config: TuneConfig | None = None,
                run_config=None) -> "Tuner":
        """Resume a run from ``{storage_path}/{name}``: finished trials
        keep their results; unfinished ones re-run from their last
        checkpoint."""
        import os
        import pickle

        state_file = os.path.join(path, "experiment_state.pkl")
        with open(state_file, "rb") as f:
            state = pickle.load(f)
        if run_config is None:
            from ray_tpu.train.config import RunConfig

            run_config = RunConfig(
                storage_path=os.path.dirname(path.rstrip("/")),
                name=os.path.basename(path.rstrip("/")))
        return cls(trainable, tune_config=tune_config,
                   run_config=run_config, _restored_trials=state)

    # ----------------------------------------------------------------- fit

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()

        trainable = self.trainable
        if isinstance(trainable, type):
            trainable = _class_trainable_loop(trainable, tc.max_iterations)

        results_queue: queue.Queue = queue.Queue()
        trials: dict[str, TrialResult] = {}
        stop_events: dict[str, threading.Event] = {}
        resume_ckpts: dict[str, Checkpoint | None] = {}
        pending: list[tuple[str, dict]] = []
        done: set[str] = set()

        if self._restored_trials is not None:
            for rec in self._restored_trials:
                trial_id = rec["trial_id"]
                trial = TrialResult(trial_id=trial_id, config=rec["config"],
                                    metrics=rec["metrics"],
                                    history=rec["history"])
                if rec["checkpoint_path"]:
                    trial.checkpoint = Checkpoint(rec["checkpoint_path"])
                trials[trial_id] = trial
                stop_events[trial_id] = threading.Event()
                if rec["status"] == "DONE":
                    done.add(trial_id)
                else:
                    resume_ckpts[trial_id] = trial.checkpoint
                    pending.append((trial_id, rec["config"]))
        elif tc.search_alg is None:
            variants = generate_variants(self.param_space, tc.num_samples,
                                         tc.seed)
            if not variants:
                variants = [{}]
            for i, config in enumerate(variants):
                trial_id = f"trial_{i:05d}_{uuid.uuid4().hex[:6]}"
                trials[trial_id] = TrialResult(trial_id=trial_id,
                                               config=config)
                stop_events[trial_id] = threading.Event()
                pending.append((trial_id, config))

        # Searcher-driven mode: trials are created lazily so each
        # suggestion can condition on completed results (reference:
        # search-algo integrations under tune/search/). On restore, the
        # searcher is replayed with the restored completions and keeps
        # issuing until num_samples total trials exist.
        searcher = tc.search_alg
        issued = [len(trials)]
        if searcher is not None:
            searcher.set_search_properties(tc.metric, tc.mode,
                                           self.param_space)
            if self._restored_trials is not None:
                for t in trials.values():
                    if t.trial_id in done and t.error is None                             and t.metrics:
                        searcher.on_trial_complete(t.trial_id, t.metrics)

        max_concurrent = tc.max_concurrent_trials or (
            max(len(pending), 1) if searcher is None else 1)
        running: set[str] = set()
        # Trials stopped by an EXPLOIT decision, awaiting relaunch with
        # (new_config, source_checkpoint).
        exploiting: dict[str, tuple[dict, Checkpoint | None]] = {}

        run_trial = ray_tpu.remote(_run_trial_fn)

        def launch(trial_id: str, config: dict,
                   ckpt: Checkpoint | None) -> None:
            running.add(trial_id)
            stop_events[trial_id] = threading.Event()
            run_trial.options(name=trial_id).remote(
                trainable, config, trial_id, results_queue,
                stop_events[trial_id], ckpt)

        def launch_next():
            while pending and len(running) < max_concurrent:
                trial_id, config = pending.pop(0)
                launch(trial_id, config, resume_ckpts.get(trial_id))
            while (searcher is not None and len(running) < max_concurrent
                   and issued[0] < tc.num_samples):
                trial_id = f"trial_{issued[0]:05d}_{uuid.uuid4().hex[:6]}"
                config = searcher.suggest(trial_id)
                if config is None:
                    break
                issued[0] += 1
                trials[trial_id] = TrialResult(trial_id=trial_id,
                                               config=config)
                stop_events[trial_id] = threading.Event()
                launch(trial_id, config, None)

        launch_next()
        run_cfg = self.run_config
        exp_dir = self._experiment_dir()
        # Per-TRIAL checkpoint managers (reference: each trial owns its
        # directory): a shared top-K across trials would evict the very
        # checkpoints PBT exploit and restore() rely on.
        managers: dict[str, Any] = {}

        def trial_manager(trial_id: str):
            if exp_dir is None:
                return None
            if trial_id not in managers:
                from ray_tpu.train.checkpoint import CheckpointManager

                managers[trial_id] = CheckpointManager(
                    f"{exp_dir}/{trial_id}",
                    num_to_keep=run_cfg.checkpoint_config.num_to_keep,
                    metric=tc.metric, mode=tc.mode)
            return managers[trial_id]

        last_state_save = 0.0
        stop_criteria = (run_cfg.stop if run_cfg is not None else None) or {}
        deadline = (time.monotonic() + tc.time_budget_s
                    if tc.time_budget_s else None)
        timed_out = False
        while len(done) < len(trials):
            if deadline is not None and time.monotonic() > deadline:
                timed_out = True
                break
            try:
                msg = results_queue.get(timeout=1.0)
            except queue.Empty:
                continue
            trial = trials[msg["trial_id"]]
            if msg.get("done"):
                if trial.trial_id in exploiting:
                    # PBT relaunch: same trial, mutated config, source ckpt.
                    new_config, ckpt = exploiting.pop(trial.trial_id)
                    trial.config = new_config
                    running.discard(trial.trial_id)
                    launch(trial.trial_id, new_config, ckpt)
                    continue
                if msg.get("error") is not None:
                    trial.error = msg["error"]
                done.add(trial.trial_id)
                running.discard(trial.trial_id)
                if searcher is not None:
                    searcher.on_trial_complete(
                        trial.trial_id, trial.metrics,
                        error=trial.error is not None)
                if exp_dir is not None:
                    self._save_state(exp_dir, trials, done)
                launch_next()
                continue
            metrics = dict(msg.get("metrics") or {})
            metrics.setdefault("training_iteration", msg.get("iteration", 0))
            trial.metrics = metrics
            trial.history.append(metrics)
            if msg.get("checkpoint") is not None:
                trial.checkpoint = msg["checkpoint"]
                manager = trial_manager(trial.trial_id)
                if manager is not None:
                    path = manager.register(msg["checkpoint"], metrics)
                    trial.checkpoint = Checkpoint(path)
                # Throttled (the done-path saves unconditionally): a full
                # state rewrite per report would be O(iterations^2) I/O.
                if exp_dir is not None and \
                        time.monotonic() - last_state_save > 1.0:
                    last_state_save = time.monotonic()
                    self._save_state(exp_dir, trials, done)
            if searcher is not None:
                searcher.on_trial_result(trial.trial_id, metrics)
            if hasattr(scheduler, "on_trial_state"):
                scheduler.on_trial_state(trial.trial_id, trial.config,
                                         trial.checkpoint)
            decision = scheduler.on_result(trial.trial_id, metrics)
            if decision == STOP:
                stop_events[trial.trial_id].set()
            elif decision == EXPLOIT:
                exploiting[trial.trial_id] = scheduler.exploit(trial.trial_id)
                stop_events[trial.trial_id].set()
            for key, threshold in stop_criteria.items():
                if key in metrics and metrics[key] >= threshold:
                    stop_events[trial.trial_id].set()
            if msg.get("ack") is not None:
                msg["ack"].set()
        if timed_out:
            budget_error = TimeoutError(
                f"tune run exceeded time_budget_s={tc.time_budget_s}")
            for trial_id in set(trials) - done:
                stop_events[trial_id].set()
                trials[trial_id].error = budget_error
            # Unblock any trial waiting on a report ack.
            try:
                while True:
                    msg = results_queue.get_nowait()
                    if msg.get("ack") is not None:
                        msg["ack"].set()
            except queue.Empty:
                pass
            if exp_dir is not None:
                # Interrupted trials persist as PENDING so restore()
                # re-runs them from their last checkpoint.
                self._save_state(exp_dir, trials, done)
        elif exp_dir is not None:
            self._save_state(exp_dir, trials, done)
        return ResultGrid(list(trials.values()), tc.metric, tc.mode)


def run(trainable, *, config: dict | None = None, num_samples: int = 1,
        metric: str = "loss", mode: str = "min", scheduler=None,
        max_concurrent_trials: int = 0) -> ResultGrid:
    """Legacy entry point (reference: tune.run, tune.py:277)."""
    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler,
                               max_concurrent_trials=max_concurrent_trials))
    return tuner.fit()
