"""ray_tpu.experimental — internal KV and channels.

Reference: python/ray/experimental/ (internal_kv.py — driver/library
access to the GCS KV; channel.py — compiled-DAG channels).
"""

from ray_tpu.experimental.internal_kv import (
    internal_kv_del,
    internal_kv_exists,
    internal_kv_get,
    internal_kv_list,
    internal_kv_put,
)

__all__ = [
    "internal_kv_del",
    "internal_kv_exists",
    "internal_kv_get",
    "internal_kv_list",
    "internal_kv_put",
]
