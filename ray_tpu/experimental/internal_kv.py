"""Internal KV — library access to the GCS key-value store.

Reference: python/ray/experimental/internal_kv.py (_internal_kv_get/
put/del/list backed by the GCS InternalKV service). When the runtime
is connected to a head (init(address=...)), operations go to the
CLUSTER KV so every driver/job sees the same namespace; otherwise the
local GCS KV serves.
"""

from __future__ import annotations

from ray_tpu._private.worker import auto_init


def _target():
    runtime = auto_init()
    if runtime.gcs_client is not None:
        return runtime.gcs_client, None
    return None, runtime.gcs.kv


def internal_kv_put(key: bytes, value: bytes,
                    namespace: str = "default") -> None:
    client, kv = _target()
    if client is not None:
        client.call("kv_put", bytes(key), bytes(value), namespace)
    else:
        kv.put(bytes(key), bytes(value), namespace)


def internal_kv_get(key: bytes, namespace: str = "default") -> bytes | None:
    client, kv = _target()
    if client is not None:
        return client.call("kv_get", bytes(key), namespace)
    return kv.get(bytes(key), namespace)


def internal_kv_del(key: bytes, namespace: str = "default") -> bool:
    client, kv = _target()
    if client is not None:
        return client.call("kv_del", bytes(key), namespace)
    return kv.delete(bytes(key), namespace)


def internal_kv_exists(key: bytes, namespace: str = "default") -> bool:
    client, kv = _target()
    if client is not None:
        return client.call("kv_exists", bytes(key), namespace)
    return kv.exists(bytes(key), namespace)


def internal_kv_list(prefix: bytes = b"",
                     namespace: str = "default") -> list[bytes]:
    client, kv = _target()
    if client is not None:
        return client.call("kv_keys", bytes(prefix), namespace)
    return kv.keys(bytes(prefix), namespace)
