"""BC / MARWIL — offline RL from logged experience.

Reference: rllib/algorithms/bc/ (behavior cloning: supervised
log-likelihood on logged actions) and rllib/algorithms/marwil/
(monotonic advantage re-weighted imitation learning — BC weighted by
exp(beta * advantage), so better-than-average logged actions are
imitated harder; BC is MARWIL with beta=0). Offline IO
(rllib/offline/) reads logged episodes; here the input is a
ray_tpu.data Dataset (or a list of dicts), so offline training rides
the same streaming data plane as everything else.

The loss is one jitted update on [B] batches of (obs, action,
advantage-ish weight); no environment interaction happens (env
metrics come from optional evaluation rollouts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import (
    categorical_entropy,
    categorical_logp,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0              # 0 => pure BC
        self.vf_coeff = 1.0          # value branch for the advantage
        self.bc_logstd_coeff = 0.0
        self.entropy_coeff = 0.0
        self.train_batch_size = 256
        self.updates_per_iteration = 32
        # offline_data(): dataset of rows with at least
        # {"obs": [obs_dim], "actions": int} (+ optional "rewards").
        self.input_ = None
        # Optional evaluation rollouts (greedy) per iteration.
        self.evaluation_num_episodes = 0

    def offline_data(self, input_) -> "MARWILConfig":
        """Reference: AlgorithmConfig.offline_data(input_=...)."""
        self.input_ = input_
        return self

    def evaluation(self, *, evaluation_num_episodes: int | None = None,
                   ) -> "MARWILConfig":
        if evaluation_num_episodes is not None:
            self.evaluation_num_episodes = evaluation_num_episodes
        return self

    def learner_class(self):
        return MARWILLearner


class BCConfig(MARWILConfig):
    """BC = MARWIL with beta=0 (reference: bc/bc.py subclasses
    MARWIL the same way)."""

    def __init__(self):
        super().__init__()
        self.beta = 0.0


class MARWILLearner(Learner):
    """exp(beta * A) - weighted log-likelihood loss (reference:
    marwil/torch/marwil_torch_learner.py)."""

    def compute_loss(self, params, batch, rng):
        cfg = self.config
        out = self.module.forward_train(
            params, {"obs": batch[Columns.OBS]}, rng)
        logits = out["action_logits"]
        logp = categorical_logp(logits, batch[Columns.ACTIONS])

        beta = getattr(cfg, "beta", 1.0)
        if beta > 0:
            values = out["vf_preds"]
            # Monte-Carlo return as the value target; advantage = G - V.
            returns = batch["returns"]
            advantages = jax.lax.stop_gradient(returns - values)
            weights = jnp.exp(jnp.clip(beta * advantages, -10.0, 10.0))
            vf_loss = jnp.mean(jnp.square(values - returns))
        else:
            weights = jnp.ones_like(logp)
            vf_loss = jnp.zeros(())

        bc_loss = -jnp.mean(jax.lax.stop_gradient(weights) * logp)
        entropy = jnp.mean(categorical_entropy(logits))
        total = (bc_loss + getattr(cfg, "vf_coeff", 1.0) * vf_loss
                 - getattr(cfg, "entropy_coeff", 0.0) * entropy)
        return total, {"bc_loss": bc_loss, "vf_loss": vf_loss,
                       "entropy": entropy,
                       "mean_weight": jnp.mean(weights)}


def _rows_to_batch(rows: list[dict], gamma: float) -> SampleBatch:
    """Flatten logged rows into a train batch with MC returns.

    Rows are episode-ordered with "terminateds"/"truncateds" flags (or
    independent transitions when absent — returns default to rewards).
    """
    obs = np.asarray([r["obs"] for r in rows], dtype=np.float32)
    actions = np.asarray([r["actions"] for r in rows])
    rewards = np.asarray([float(r.get("rewards", 0.0)) for r in rows],
                         dtype=np.float32)
    dones = np.asarray([bool(r.get("terminateds", False)
                             or r.get("truncateds", False))
                        for r in rows])
    if not dones.any():
        # No episode boundaries at all: treat rows as independent
        # transitions (returns = per-row rewards) rather than chaining
        # one never-resetting discounted sum across unrelated rows.
        returns = rewards.copy()
    else:
        returns = np.zeros_like(rewards)
        acc = 0.0
        for i in range(len(rows) - 1, -1, -1):
            if dones[i]:
                acc = 0.0
            acc = rewards[i] + gamma * acc
            returns[i] = acc
    return SampleBatch({
        Columns.OBS: obs,
        Columns.ACTIONS: actions,
        "returns": returns,
    })


class MARWIL(Algorithm):
    config_class = MARWILConfig

    def setup(self, config: dict) -> None:
        super().setup(config)
        cfg = self.algo_config
        from ray_tpu.rllib.algorithms.algorithm import load_offline_rows

        self._train_batch = _rows_to_batch(
            load_offline_rows(cfg.input_), cfg.gamma)
        self._rng = np.random.default_rng(cfg.seed)
        self._learner_steps = 0

    def _build_env_runners(self, cfg):
        # Offline: env runners exist only for optional evaluation.
        if cfg.evaluation_num_episodes <= 0:
            self.local_env_runner = None
            return None
        return super()._build_env_runners(cfg)

    def _sync_weights(self) -> None:
        if getattr(self, "local_env_runner", None) is None \
                and self.env_runner_group is None:
            self._weights_version += 1
            return
        super()._sync_weights()

    def training_step(self) -> dict:
        cfg = self.algo_config
        n = len(self._train_batch)
        metrics: dict = {}
        for _ in range(cfg.updates_per_iteration):
            idx = self._rng.integers(
                0, n, size=min(cfg.train_batch_size, n))
            minibatch = SampleBatch(
                {k: np.asarray(v)[idx]
                 for k, v in self._train_batch.items()})
            metrics = self.learner_group.update_from_batch(minibatch)
            self._learner_steps += 1
        results = dict(metrics)
        results["num_learner_steps"] = self._learner_steps

        if cfg.evaluation_num_episodes > 0:
            results.update(self._evaluate(cfg))
        return results

    def _evaluate(self, cfg) -> dict:
        """Greedy rollouts with the current policy on the LOCAL runner
        (reference: evaluation_config with explore=False; offline
        evaluation keeps num_env_runners=0)."""
        self._sync_weights()
        runner = self.local_env_runner
        if runner is None:
            return {}
        # Accumulate across rounds until the episode target is met
        # (get_metrics drains, so each round's mean is weighted by its
        # episode count).
        episodes = 0
        weighted_return = 0.0
        rounds = 0
        while episodes < cfg.evaluation_num_episodes and rounds < 50:
            runner.sample()
            rounds += 1
            m = runner.get_metrics()
            n = m.get("num_episodes", 0)
            if n:
                episodes += n
                weighted_return += m["episode_return_mean"] * n
        if episodes == 0:
            return {}
        return {"evaluation_return_mean": weighted_return / episodes,
                "evaluation_num_episodes": episodes}


class BC(MARWIL):
    config_class = BCConfig


MARWILConfig.algo_class = MARWIL
BCConfig.algo_class = BC
