"""APEX-DQN — distributed prioritized experience replay.

Reference: rllib/algorithms/apex_dqn/ (Horgan et al. 2018): many env
runners explore with a per-runner epsilon ladder, transitions flow into
sharded prioritized replay actors, a central learner samples from the
shards asynchronously and streams priority corrections back.

Runtime shape here:

- env runners are process actors sampling with a bounded in-flight
  request pool (the IMPALA pump);
- each replay shard is a process actor wrapping
  PrioritizedReplayBuffer; fragments are pushed round-robin as object
  refs so the driver never relays transition bytes to the shard;
- the learner (TPU) samples from shards round-robin, runs the jitted
  DQN update, and fires priority updates back at the owning shard
  without awaiting them.
"""

from __future__ import annotations

import collections

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.dqn import DQNConfig
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer
from ray_tpu.rllib.utils.sample_batch import (
    Columns,
    SampleBatch,
    fragment_to_transitions,
)


class ReplayShard:
    """One shard of the distributed replay (reference: apex's
    ReplayActor). Runs as a process actor so buffer inserts and
    priority maintenance never contend with the driver's GIL."""

    def __init__(self, capacity: int, alpha: float, beta: float,
                 seed: int):
        self.buffer = PrioritizedReplayBuffer(
            capacity, alpha=alpha, beta=beta, seed=seed)

    def add(self, transitions: SampleBatch) -> int:
        self.buffer.add(SampleBatch(transitions))
        return len(self.buffer)

    def sample(self, batch_size: int, min_size: int):
        if len(self.buffer) < max(min_size, batch_size):
            return None
        return self.buffer.sample(batch_size)

    def update_priorities(self, idx, td) -> None:
        self.buffer.update_priorities(np.asarray(idx), np.asarray(td))

    def size(self) -> int:
        return len(self.buffer)


class ApexDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 2
        self.num_replay_shards = 1
        self.replay_shard_capacity = 50_000
        self.prioritized_replay = True
        self.replay_alpha = 0.6
        self.replay_beta = 0.4
        # Per-runner epsilon ladder: eps_i = base^(1 + i*alpha/(N-1))
        # (Horgan et al. eq. 1) — runner 0 explores the most.
        self.epsilon_base = 0.4
        self.epsilon_ladder_alpha = 7.0
        self.max_requests_in_flight_per_env_runner = 2
        self.updates_per_iteration = 16
        self.broadcast_interval = 4      # learner steps between pushes
        self.num_steps_sampled_before_learning = 1000

    def learner_class(self):
        from ray_tpu.rllib.algorithms.dqn import DQNLearner
        return DQNLearner


class ApexDQN(Algorithm):
    config_class = ApexDQNConfig

    def setup(self, config: dict) -> None:
        super().setup(config)
        cfg = self.algo_config
        RemoteShard = ray_tpu.remote(ReplayShard).options(process=True)
        self._shards = [
            RemoteShard.remote(cfg.replay_shard_capacity,
                               cfg.replay_alpha, cfg.replay_beta,
                               cfg.seed + i)
            for i in range(max(1, cfg.num_replay_shards))]
        self._shard_rr = 0          # round-robin insert cursor
        self._pending: list = []    # sample() requests in flight
        self._push_refs: collections.deque = collections.deque(maxlen=64)
        self._learner_steps = 0
        self._total_added = 0

    def _build_env_runners(self, cfg):
        """Per-runner epsilon ladder: each runner gets a CONSTANT
        epsilon from the ladder instead of the decay schedule (the
        ladder replaces annealing in apex)."""
        if cfg.num_env_runners <= 0:
            return super()._build_env_runners(cfg)
        n = cfg.num_env_runners
        RemoteRunner = ray_tpu.remote(SingleAgentEnvRunner)
        if getattr(cfg, "use_process_runners", False):
            RemoteRunner = RemoteRunner.options(process=True)

        def ladder(idx: int) -> float:
            if n == 1:
                return cfg.epsilon_base
            return cfg.epsilon_base ** (
                1.0 + idx * cfg.epsilon_ladder_alpha / (n - 1))

        def factory(idx: int):
            spec = self.module_spec
            spec = type(spec)(
                module_class=spec.module_class,
                observation_size=spec.observation_size,
                num_actions=spec.num_actions,
                action_size=getattr(spec, "action_size", 0),
                model_config={**spec.model_config,
                              "epsilon_start": ladder(idx),
                              "epsilon_end": ladder(idx)})
            return RemoteRunner.remote(
                env_id=cfg.env, module_spec=spec,
                num_envs=cfg.num_envs_per_env_runner,
                rollout_fragment_length=cfg.rollout_fragment_length,
                seed=cfg.seed, worker_index=idx + 1, explore=cfg.explore)

        actors = [factory(i) for i in range(n)]
        self.local_env_runner = None
        return FaultTolerantActorManager(actors, actor_factory=factory)

    # -- sampling pump (shared with IMPALA: actor_manager.pump) -------
    def _pump_sampling(self) -> None:
        group = self.env_runner_group
        if group is None:
            self._ingest_fragment(self.local_env_runner.sample())
            return
        self._pending = group.pump(
            "sample", self._pending, self._ingest_fragment)

    def _ingest_fragment(self, frag: SampleBatch) -> None:
        T, B = np.shape(frag[Columns.OBS])[:2]
        self._timesteps_total += T * B
        transitions = fragment_to_transitions(frag)
        if len(transitions) == 0:
            return
        self._total_added += len(transitions)
        shard = self._shards[self._shard_rr % len(self._shards)]
        self._shard_rr += 1
        # Fire-and-forget insert; the bounded deque retains refs long
        # enough to observe errors without blocking the pump.
        self._push_refs.append(shard.add.remote(transitions))

    def training_step(self) -> dict:
        cfg = self.algo_config
        metrics: dict = {}
        self._pump_sampling()
        min_size = (cfg.num_steps_sampled_before_learning
                    // max(1, len(self._shards)))

        def request(i: int):
            shard = self._shards[i % len(self._shards)]
            return shard, shard.sample.remote(
                cfg.train_batch_size, min_size)

        # Prefetch pipeline: the request for update i+1 is in flight
        # while update i runs on the learner, hiding the shard-actor
        # round trip behind the jitted update. The producing shard
        # rides with each ref — priority corrections must go back to
        # the shard the batch came from. The LAST update consumes its
        # batch without issuing a successor (an abandoned request would
        # still cost the shard a full prioritized sampling pass).
        max_attempts = 4 * max(1, cfg.updates_per_iteration)
        shard, next_ref = request(0)
        updates = 0
        attempts = 0
        while True:
            attempts += 1
            batch = ray_tpu.get(next_ref)
            producer = shard
            # Another get happens iff the loop will run again; only
            # then is a successor request worth its sampling cost.
            more = (updates + (0 if batch is None else 1)
                    < cfg.updates_per_iteration
                    and attempts < max_attempts)
            if more:
                shard, next_ref = request(attempts)
            if batch is None:
                if not more:
                    break
                # Shards still warming up: keep sampling instead.
                self._pump_sampling()
                continue
            batch = SampleBatch(batch)
            indexes = batch.pop("batch_indexes")
            metrics = self.learner_group.update_from_batch(batch)
            td = self.learner_group.call(
                "compute_td_errors",
                SampleBatch({k: v for k, v in batch.items()
                             if k != "weights"}))
            # Priority correction streams back without a driver wait.
            producer.update_priorities.remote(indexes, td)
            updates += 1
            self._learner_steps += 1
            if self._learner_steps % cfg.broadcast_interval == 0:
                self._sync_weights()
            if not more:
                break

        results = self._runner_metrics()
        results.update(metrics)
        results["num_learner_steps"] = self._learner_steps
        results["num_transitions_added"] = self._total_added
        results["replay_shard_sizes"] = ray_tpu.get(
            [s.size.remote() for s in self._shards])
        return results

    def cleanup(self) -> None:
        for shard in getattr(self, "_shards", []):
            try:
                ray_tpu.kill(shard)
            except Exception:
                pass  # shard already dead at teardown
        super().cleanup()


ApexDQNConfig.algo_class = ApexDQN
