"""SimpleQ — minimal Q-learning (DQN without the extensions).

Reference: rllib/algorithms/simple_q/ (vanilla Q-learning: single
target network, no double-Q, no prioritized replay, no dueling — the
pedagogical baseline the full DQN layers on top of). Here it is DQN
with the extensions switched off, which is exactly how the reference
relates the two families.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.double_q = False
        self.prioritized_replay = False
        self.target_update_freq = 100
        self.updates_per_iteration = 16


class SimpleQ(DQN):
    config_class = SimpleQConfig


SimpleQConfig.algo_class = SimpleQ
