"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Reference: rllib/algorithms/bandit/ (BanditLinUCB / BanditLinTS —
closed-form linear bandits over per-arm design matrices, no neural
learner). TPU shape: the per-round arm scoring and the rank-1 design
updates are vectorized over arms and batch lanes as dense linear
algebra (solve/einsum) — one numpy/LAPACK call per round rather than
per-arm Python loops.

Environment contract: a :class:`LinearContextualBanditEnv`-style object
with ``num_arms``, ``context_size``, ``observe(B) -> contexts [B, d]``,
``pull(contexts, arms) -> rewards [B]``, and ``optimal(contexts) ->
(best_arms, best_rewards)`` for regret accounting.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class LinearContextualBanditEnv:
    """Linear rewards: r = x . theta_arm + noise (the standard testbed,
    reference: rllib's ParametricLinearBanditEnv family)."""

    def __init__(self, num_arms: int = 5, context_size: int = 8,
                 noise: float = 0.05, seed: int = 0):
        self.num_arms = num_arms
        self.context_size = context_size
        self.noise = noise
        rng = np.random.default_rng(seed)
        theta = rng.normal(size=(num_arms, context_size))
        self.theta = theta / np.linalg.norm(theta, axis=1, keepdims=True)
        self._rng = rng

    def observe(self, batch: int) -> np.ndarray:
        x = self._rng.normal(size=(batch, self.context_size))
        return (x / np.linalg.norm(x, axis=1, keepdims=True)).astype(
            np.float32)

    def pull(self, contexts: np.ndarray, arms: np.ndarray) -> np.ndarray:
        mean = np.einsum("bd,bd->b", contexts, self.theta[arms])
        return (mean + self._rng.normal(
            scale=self.noise, size=len(arms))).astype(np.float32)

    def optimal(self, contexts: np.ndarray):
        means = contexts @ self.theta.T  # [B, K]
        best = np.argmax(means, axis=1)
        return best, means[np.arange(len(best)), best]


_BANDIT_ENVS = {"LinearBandit-v0": LinearContextualBanditEnv}


def register_bandit_env(env_id: str, factory) -> None:
    _BANDIT_ENVS[env_id] = factory


class BanditConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "LinearBandit-v0"
        self.num_arms = 5
        self.context_size = 8
        self.rounds_per_iteration = 64
        self.batch_size = 16
        self.alpha = 1.0          # LinUCB exploration bonus scale
        self.lam = 1.0            # ridge regularizer on the design
        self.ts_scale = 0.5       # LinTS posterior scale

    def environment(self, env: str | None = None, **kwargs):
        if env is not None:
            self.env = env
        for key, value in kwargs.items():
            setattr(self, key, value)
        return self

    def build(self) -> "Algorithm":
        assert self.algo_class is not None
        return self.algo_class(config=self)


class _LinearBandit(Algorithm):
    """Shared closed-form machinery; subclasses pick the arm scorer."""

    config_class = BanditConfig

    def setup(self, config: dict) -> None:
        # No module/learner/env-runner stack: bandits are closed-form
        # (reference: the bandit algorithms bypass the RLModule path).
        cfg = self.algo_config
        factory = _BANDIT_ENVS.get(cfg.env)
        if factory is None:
            raise ValueError(
                f"unknown bandit env {cfg.env!r}; register it with "
                "register_bandit_env()")
        self.env = factory(num_arms=cfg.num_arms,
                           context_size=cfg.context_size,
                           seed=cfg.seed)
        K, d = self.env.num_arms, self.env.context_size
        self._rng = np.random.default_rng(cfg.seed)
        # Per-arm ridge design: A_k = lam*I + sum x x^T ; b_k = sum r x.
        self.A = np.tile(np.eye(d) * cfg.lam, (K, 1, 1))
        self.b = np.zeros((K, d))
        self.cumulative_regret = 0.0
        self.total_pulls = 0
        self.total_optimal = 0

    def _theta_hat(self) -> np.ndarray:
        return np.linalg.solve(self.A, self.b[..., None])[..., 0]  # [K,d]

    def _choose(self, contexts: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def training_step(self) -> dict:
        cfg = self.algo_config
        rewards_sum = 0.0
        for _ in range(cfg.rounds_per_iteration):
            contexts = self.env.observe(cfg.batch_size)
            arms = self._choose(contexts)
            rewards = self.env.pull(contexts, arms)
            best_arms, best_rewards = self.env.optimal(contexts)
            # Empirical regret from REALIZED rewards (reward noise is
            # mean-zero, so this is unbiased) — keeps the env contract
            # to observe/pull/optimal; custom envs need not expose
            # their mean structure.
            self.cumulative_regret += float(
                np.sum(best_rewards - rewards))
            self.total_pulls += len(arms)
            self.total_optimal += int(np.sum(arms == best_arms))
            rewards_sum += float(rewards.sum())
            # Rank-1 design updates, grouped per pulled arm.
            for arm in np.unique(arms):
                rows = contexts[arms == arm]
                self.A[arm] += rows.T @ rows
                self.b[arm] += rewards[arms == arm] @ rows
            self._timesteps_total += len(arms)
        pulls = cfg.rounds_per_iteration * cfg.batch_size
        return {
            "mean_reward": rewards_sum / pulls,
            "cumulative_regret": self.cumulative_regret,
            "regret_per_pull": self.cumulative_regret
            / max(1, self.total_pulls),
            "optimal_arm_rate": self.total_optimal
            / max(1, self.total_pulls),
        }

    def cleanup(self) -> None:  # no actors to tear down
        pass

    def save_checkpoint(self, checkpoint_dir: str):
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "bandit_state.pkl"),
                  "wb") as f:
            pickle.dump({
                "A": self.A, "b": self.b,
                "iteration": self.iteration,
                "timesteps": self._timesteps_total,
                "cumulative_regret": self.cumulative_regret,
                "total_pulls": self.total_pulls,
                "total_optimal": self.total_optimal,
            }, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint) -> None:
        import os
        import pickle

        path = checkpoint if isinstance(checkpoint, str) else \
            checkpoint.path
        with open(os.path.join(path, "bandit_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.A, self.b = state["A"], state["b"]
        self.iteration = state["iteration"]
        self._timesteps_total = state.get("timesteps", 0)
        self.cumulative_regret = state.get("cumulative_regret", 0.0)
        self.total_pulls = state.get("total_pulls", 0)
        self.total_optimal = state.get("total_optimal", 0)


class BanditLinUCB(_LinearBandit):
    """LinUCB (Li et al. 2010): score = x.theta_hat + alpha *
    sqrt(x A^-1 x) — optimism in the face of uncertainty."""

    def _choose(self, contexts: np.ndarray) -> np.ndarray:
        cfg = self.algo_config
        theta = self._theta_hat()                       # [K, d]
        means = contexts @ theta.T                      # [B, K]
        # x A_k^-1 x per (lane, arm): solve K systems for all lanes.
        Ainv_x = np.linalg.solve(
            self.A[None, :, :, :],
            np.broadcast_to(
                contexts[:, None, :, None],
                (contexts.shape[0], self.A.shape[0],
                 contexts.shape[1], 1)))                 # [B, K, d, 1]
        var = np.einsum("bd,bkd->bk", contexts, Ainv_x[..., 0])
        ucb = means + cfg.alpha * np.sqrt(np.maximum(var, 0.0))
        return np.argmax(ucb, axis=1)


class BanditLinTS(_LinearBandit):
    """Linear Thompson sampling: draw theta_k ~ N(theta_hat_k,
    v^2 A_k^-1), pick the argmax arm under the sample."""

    def _choose(self, contexts: np.ndarray) -> np.ndarray:
        cfg = self.algo_config
        theta = self._theta_hat()                       # [K, d]
        K, d = theta.shape
        Ainv = np.linalg.inv(self.A)                    # [K, d, d]
        # One posterior sample per arm per round (shared across lanes —
        # the standard batched-TS approximation).
        chol = np.linalg.cholesky(
            Ainv + 1e-9 * np.eye(d)[None])              # [K, d, d]
        eps = self._rng.normal(size=(K, d, 1))
        sampled = theta + cfg.ts_scale * (chol @ eps)[..., 0]
        scores = contexts @ sampled.T                   # [B, K]
        return np.argmax(scores, axis=1)


class BanditLinUCBConfig(BanditConfig):
    algo_class = BanditLinUCB


class BanditLinTSConfig(BanditConfig):
    algo_class = BanditLinTS
