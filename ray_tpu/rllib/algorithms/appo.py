"""APPO — Asynchronous PPO (IMPALA architecture + clipped surrogate).

Reference: rllib/algorithms/appo/appo.py (APPO extends IMPALA; config
adds use_kl_loss/clip_param/target-network) and
appo/torch/appo_torch_learner.py (loss: V-trace advantages fed into the
PPO clip objective, plus a KL term against the TARGET policy — the
slow-moving network that generated... is periodically snapshotted from
the online one).

TPU shape: inherits IMPALA's async sampling/queue loop unchanged; the
loss swap and the target-params snapshot are the only deltas. Target
params ride inside the batch (same trick as DQN) so the jitted update
stays pure.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala import (
    IMPALA,
    IMPALAConfig,
    IMPALALearner,
    vtrace,
)
from ray_tpu.rllib.core.rl_module import (
    categorical_entropy,
    categorical_kl,
    categorical_logp,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.4
        self.use_kl_loss = True
        self.kl_coeff = 1.0
        self.kl_target = 0.01
        # Learner steps between target-network snapshots (reference:
        # appo.py target_network_update_freq, counted in env steps there).
        self.target_update_frequency = 4

    def learner_class(self):
        return APPOLearner


class APPOLearner(IMPALALearner):
    """Clipped-surrogate + V-trace loss with target-policy KL."""

    def __init__(self, module_spec, config=None, mesh=None):
        super().__init__(module_spec, config, mesh)
        self.target_params = jax.tree_util.tree_map(
            jnp.copy, self.params)
        # Adaptive KL coefficient (host-side state, like the reference's
        # kl_coeff update in appo_torch_learner.py).
        self.kl_coeff = float(getattr(config, "kl_coeff", 1.0))

    def compute_loss(self, params, batch, rng):
        cfg = self.config
        T, B = batch[Columns.REWARDS].shape
        flat = {"obs": batch[Columns.OBS].reshape(
            (T * B,) + batch[Columns.OBS].shape[2:])}
        out = self.module.forward_train(params, flat, rng)
        logits = out["action_logits"].reshape(T, B, -1)
        values = out["vf_preds"].reshape(T, B)

        target_out = self.module.forward_train(
            batch["target_params"], flat, rng)
        target_logits = jax.lax.stop_gradient(
            target_out["action_logits"].reshape(T, B, -1))

        target_logp = categorical_logp(logits, batch[Columns.ACTIONS])
        behavior_logp = batch[Columns.ACTION_LOGP]
        vs, pg_adv = vtrace(
            behavior_logp, jax.lax.stop_gradient(target_logp),
            batch[Columns.REWARDS], jax.lax.stop_gradient(values),
            batch["bootstrap_value"], batch[Columns.TERMINATEDS],
            batch[Columns.TRUNCATEDS], cfg.gamma,
            cfg.clip_rho_threshold, cfg.clip_c_threshold)

        ratio = jnp.exp(target_logp - behavior_logp)
        surrogate = jnp.minimum(
            pg_adv * ratio,
            pg_adv * jnp.clip(ratio, 1 - cfg.clip_param,
                              1 + cfg.clip_param))
        pg_loss = -jnp.mean(surrogate)
        vf_loss = 0.5 * jnp.mean(jnp.square(values - vs))
        entropy = jnp.mean(categorical_entropy(logits))
        kl = jnp.mean(categorical_kl(target_logits, logits))

        total = (pg_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        if getattr(cfg, "use_kl_loss", True):
            total = total + batch["kl_coeff"] * kl
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy, "kl": kl}

    def update_from_batch(self, batch: SampleBatch,
                          sync_metrics: bool = True) -> dict:
        # The adaptive-KL controller below reads metrics["kl"] on host,
        # so APPO always syncs regardless of the caller's preference.
        batch = SampleBatch(batch)
        batch["target_params"] = self.target_params
        batch["kl_coeff"] = jnp.asarray(self.kl_coeff, dtype=jnp.float32)
        metrics = super().update_from_batch(batch)
        # Adaptive KL coefficient (reference: appo_torch_learner.py
        # after_gradient_based_update).
        cfg = self.config
        kl = metrics.get("kl", 0.0)
        if kl > 2.0 * cfg.kl_target:
            self.kl_coeff *= 1.5
        elif kl < 0.5 * cfg.kl_target:
            self.kl_coeff *= 0.5
        metrics["kl_coeff"] = self.kl_coeff
        # Periodic target snapshot.
        if self._steps % getattr(cfg, "target_update_frequency", 4) == 0:
            self.target_params = jax.tree_util.tree_map(
                jnp.copy, self.params)
        return metrics

    def get_state(self) -> dict:
        state = super().get_state()
        state["target_params"] = jax.device_get(self.target_params)
        state["kl_coeff"] = self.kl_coeff
        return state

    def set_state(self, state: dict) -> None:
        super().set_state(state)
        if "target_params" in state:
            self.target_params = jax.tree_util.tree_map(
                jnp.asarray, state["target_params"])
        self.kl_coeff = state.get("kl_coeff", self.kl_coeff)


class APPO(IMPALA):
    config_class = APPOConfig


APPOConfig.algo_class = APPO
