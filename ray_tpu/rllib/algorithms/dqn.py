"""DQN — off-policy Q-learning with target network and replay.

Reference: rllib/algorithms/dqn/ (new-stack DQN/Rainbow-lite:
double-Q + target net + optional prioritized replay). The TD-error and
update are one jitted function; the target network is a second params
pytree swapped by `optax.periodic_update`-style copying.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import TargetNetworkLearner
from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec, _mlp_apply, _mlp_init
from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.utils.sample_batch import (
    Columns,
    SampleBatch,
    fragment_to_transitions,
)


class QNetworkModule(RLModule):
    """MLP Q-network; exploration is epsilon-greedy with a linear decay
    schedule computed INSIDE the jitted forward from the runner's step
    counter (batch["t"]), so epsilon changes every step without ever
    retracing."""

    def __init__(self, observation_size: int, num_actions: int,
                 hidden: tuple = (64, 64), epsilon_start: float = 1.0,
                 epsilon_end: float = 0.05,
                 epsilon_decay_steps: int = 10_000, **_):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.hidden = tuple(hidden)
        self.epsilon_start = epsilon_start
        self.epsilon_end = epsilon_end
        self.epsilon_decay_steps = epsilon_decay_steps

    def init(self, rng):
        sizes = (self.observation_size,) + self.hidden + (self.num_actions,)
        return {"q": _mlp_init(rng, sizes)}

    def q_values(self, params, obs):
        return _mlp_apply(params["q"], obs)

    def forward_inference(self, params, batch, rng=None):
        q = self.q_values(params, batch["obs"])
        return {"action_logits": q, "actions": jnp.argmax(q, axis=-1)}

    def forward_exploration(self, params, batch, rng=None):
        q = self.q_values(params, batch["obs"])
        greedy = jnp.argmax(q, axis=-1)
        t = batch.get("t", self.epsilon_decay_steps)
        frac = jnp.clip(t / self.epsilon_decay_steps, 0.0, 1.0)
        eps = self.epsilon_start + frac * (
            self.epsilon_end - self.epsilon_start)
        explore_rng, action_rng = jax.random.split(rng)
        random_actions = jax.random.randint(
            action_rng, greedy.shape, 0, self.num_actions)
        take_random = jax.random.uniform(
            explore_rng, greedy.shape) < eps
        actions = jnp.where(take_random, random_actions, greedy)
        return {"action_logits": q, "actions": actions,
                "action_logp": jnp.zeros_like(q[..., 0]),
                "vf_preds": jnp.max(q, axis=-1)}

    def forward_train(self, params, batch, rng=None):
        return {"action_logits": self.q_values(params, batch["obs"])}


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.module_class = QNetworkModule
        self.lr = 5e-4
        self.buffer_capacity = 50_000
        self.prioritized_replay = False
        self.train_batch_size = 64
        self.target_update_freq = 200     # learner steps
        self.num_steps_sampled_before_learning = 1000
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 10_000
        self.double_q = True
        self.updates_per_iteration = 32

    def learner_class(self):
        return DQNLearner

    def module_spec(self):
        spec = super().module_spec()
        spec.model_config.setdefault("epsilon_start", self.epsilon_start)
        spec.model_config.setdefault("epsilon_end", self.epsilon_end)
        spec.model_config.setdefault("epsilon_decay_steps",
                                     self.epsilon_decay_steps)
        return spec


class DQNLearner(TargetNetworkLearner):
    def compute_loss(self, params, batch, rng):
        cfg = self.config
        q = self.module.q_values(params, batch[Columns.OBS])
        q_taken = jnp.take_along_axis(
            q, batch[Columns.ACTIONS][..., None].astype(jnp.int32),
            axis=-1)[..., 0]

        # Target params ride inside the batch so the jitted loss stays a
        # pure function of its inputs (a closed-over pytree would be
        # baked in as a compile-time constant and never update).
        q_next_target = self.module.q_values(
            batch["target_params"], batch[Columns.NEXT_OBS])
        if getattr(cfg, "double_q", True):
            q_next_online = self.module.q_values(
                params, batch[Columns.NEXT_OBS])
            next_actions = jnp.argmax(q_next_online, axis=-1)
            q_next = jnp.take_along_axis(
                q_next_target, next_actions[..., None], axis=-1)[..., 0]
        else:
            q_next = jnp.max(q_next_target, axis=-1)

        not_done = 1.0 - batch[Columns.TERMINATEDS].astype(jnp.float32)
        targets = batch[Columns.REWARDS] + cfg.gamma * not_done * q_next
        td_error = q_taken - jax.lax.stop_gradient(targets)
        weights = batch.get("weights", jnp.ones_like(td_error))
        loss = jnp.mean(weights * jnp.square(td_error))
        return loss, {"td_error_mean": jnp.mean(jnp.abs(td_error)),
                      "q_mean": jnp.mean(q_taken)}

    def compute_td_errors(self, batch: SampleBatch) -> np.ndarray:
        """Per-row |TD error| for priority updates (post-update params)."""
        if not hasattr(self, "_td_fn"):
            def td_fn(params, batch):
                cfg = self.config
                q = self.module.q_values(params, batch[Columns.OBS])
                q_taken = jnp.take_along_axis(
                    q, batch[Columns.ACTIONS][..., None].astype(jnp.int32),
                    axis=-1)[..., 0]
                q_next_target = self.module.q_values(
                    batch["target_params"], batch[Columns.NEXT_OBS])
                q_next = jnp.max(q_next_target, axis=-1)
                not_done = 1.0 - batch[Columns.TERMINATEDS].astype(
                    jnp.float32)
                targets = (batch[Columns.REWARDS]
                           + cfg.gamma * not_done * q_next)
                return jnp.abs(q_taken - targets)
            self._td_fn = jax.jit(td_fn)
        b = SampleBatch(batch)
        b["target_params"] = self.target_params
        return np.asarray(self._td_fn(self.params, self._device_batch(b)))


class DQN(Algorithm):
    config_class = DQNConfig

    def setup(self, config: dict) -> None:
        super().setup(config)
        cfg = self.algo_config
        buf_cls = (PrioritizedReplayBuffer if cfg.prioritized_replay
                   else ReplayBuffer)
        self.replay = buf_cls(cfg.buffer_capacity, seed=cfg.seed)
        self._learner_steps = 0

    def _fragment_to_transitions(self, frag: SampleBatch) -> SampleBatch:
        """[T, B] fragment -> flat (s, a, r, s', done) rows (shared
        truncation-boundary logic — see
        utils/sample_batch.fragment_to_transitions)."""
        return fragment_to_transitions(frag)

    def training_step(self) -> dict:
        cfg = self.algo_config
        fragments = self._sample_fragments()
        for frag in fragments:
            self.replay.add(self._fragment_to_transitions(frag))

        metrics: dict = {}
        if len(self.replay) >= cfg.num_steps_sampled_before_learning:
            for _ in range(cfg.updates_per_iteration):
                batch = self.replay.sample(cfg.train_batch_size)
                metrics = self.learner_group.update_from_batch(batch)
                self._learner_steps += 1
                if cfg.prioritized_replay and "batch_indexes" in batch:
                    td = self.learner_group.call(
                        "compute_td_errors",
                        SampleBatch({k: v for k, v in batch.items()
                                     if k not in ("weights",
                                                  "batch_indexes")}))
                    self.replay.update_priorities(
                        batch["batch_indexes"], td)
            self._sync_weights()

        results = self._runner_metrics()
        results.update(metrics)
        results["replay_buffer_size"] = len(self.replay)
        results["num_learner_steps"] = self._learner_steps
        return results


DQNConfig.algo_class = DQN
