"""Algorithm + AlgorithmConfig — the RLlib-equivalent driver layer.

Reference: rllib/algorithms/algorithm.py:195 (Algorithm extends Tune's
Trainable; step :807, training_step :1597) and algorithm_config.py
(fluent builder). An Algorithm owns:

- a FaultTolerantActorManager of SingleAgentEnvRunner actors (CPU), and
- a LearnerGroup (TPU) holding the jitted update,

and its ``training_step`` moves sample fragments from the first to the
second through the object store, then broadcasts weights back.
"""

from __future__ import annotations

import copy
import pickle
import os
import time
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import (
    DefaultActorCriticModule,
    RLModuleSpec,
)
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.vector_env import make_vector_env
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.utils.sample_batch import SampleBatch
from ray_tpu.tune import Trainable


class AlgorithmConfig:
    """Fluent config builder (reference: algorithm_config.py).

    Usage::

        config = (PPOConfig()
                  .environment("CartPole-v1")
                  .env_runners(num_env_runners=2, num_envs_per_env_runner=8)
                  .training(lr=3e-4, gamma=0.99))
        algo = config.build()
    """

    algo_class: type | None = None

    def __init__(self):
        # environment()
        self.env = "CartPole-v1"
        # env_runners()
        self.num_env_runners = 0  # 0 = sample in the driver process
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 64
        self.explore = True
        # Remote runners default to dedicated OS processes: a thread
        # fleet shares one GIL and caps rollout throughput at a single
        # core (reference: env runners are separate worker processes by
        # construction).
        self.use_process_runners = True
        # training()
        self.lr = 3e-4
        self.gamma = 0.99
        self.grad_clip = None
        self.train_batch_size = 512
        self.minibatch_size = 128
        self.num_epochs = 1
        # learners()
        self.num_learners = 0  # 0 = single local learner
        # Devices for the local learner's data mesh: 1 = single device,
        # -1 = all local devices (GSPMD shards the batch; XLA inserts the
        # gradient all-reduce over ICI).
        self.num_devices_per_learner = 1
        # offline_output()
        self.output: str | None = None
        self.output_format = "parquet"
        # rl_module()
        self.model_config: dict = {"hidden": (64, 64)}
        self.module_class: type | None = None
        # debugging()
        self.seed = 0

    # -- fluent setters (each returns self) ---------------------------
    def environment(self, env: str) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: int | None = None,
                    num_envs_per_env_runner: int | None = None,
                    rollout_fragment_length: int | None = None,
                    explore: bool | None = None,
                    use_process_runners: bool | None = None,
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if explore is not None:
            self.explore = explore
        if use_process_runners is not None:
            self.use_process_runners = use_process_runners
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown training option: {k}")
            setattr(self, k, v)
        return self

    def offline_output(self, output: str,
                       output_format: str = "parquet",
                       ) -> "AlgorithmConfig":
        """Log every sampled fragment to experience shard files while
        training (reference: AlgorithmConfig.offline_data(output=...)
        feeding JsonWriter/DatasetWriter). Read back with
        rllib.offline.read_offline_dataset."""
        self.output = output
        self.output_format = output_format
        return self

    def learners(self, *, num_learners: int | None = None,
                 num_devices_per_learner: int | None = None,
                 ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_devices_per_learner is not None:
            self.num_devices_per_learner = num_devices_per_learner
        return self

    def rl_module(self, *, model_config: dict | None = None,
                  module_class: type | None = None) -> "AlgorithmConfig":
        if model_config is not None:
            self.model_config = model_config
        if module_class is not None:
            self.module_class = module_class
        return self

    def debugging(self, *, seed: int | None = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # -- build ---------------------------------------------------------
    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def learner_class(self) -> type:
        raise NotImplementedError

    def module_spec(self) -> RLModuleSpec:
        probe = make_vector_env(self.env, 1)
        model_config = dict(self.model_config)
        if getattr(probe, "action_size", 0):
            model_config.setdefault(
                "action_scale", getattr(probe, "action_scale", 1.0))
        return RLModuleSpec(
            module_class=self.module_class or DefaultActorCriticModule,
            observation_size=probe.observation_size,
            num_actions=probe.num_actions,
            action_size=getattr(probe, "action_size", 0),
            model_config=model_config)

    def build(self) -> "Algorithm":
        assert self.algo_class is not None
        return self.algo_class(config=self)

    def to_dict(self) -> dict:
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_")}


def load_offline_rows(input_) -> list:
    """Offline-input unwrap shared by BC/MARWIL/CQL: a ray_tpu.data
    Dataset (take_all) or any iterable of row dicts; None/empty are
    clear errors instead of shape crashes deep in the learner."""
    if input_ is None:
        raise ValueError(
            "offline algorithms need config.offline_data(input_=...): "
            "a ray_tpu.data Dataset or a list of row dicts")
    rows = (list(input_.take_all())
            if hasattr(input_, "take_all") else list(input_))
    if not rows:
        raise ValueError("offline input is empty")
    return rows


class Algorithm(Trainable):
    """Reference: rllib/algorithms/algorithm.py:195.

    ``train()`` (Trainable protocol) -> ``step()`` -> ``training_step()``
    which subclasses implement. Also usable under ray_tpu.tune.
    """

    config_class: type = AlgorithmConfig

    def __init__(self, config: "AlgorithmConfig | dict | None" = None):
        if isinstance(config, dict) or config is None:
            cfg = self.config_class()
            for k, v in (config or {}).items():
                setattr(cfg, k, v)
            config = cfg
        super().__init__(config.to_dict())
        self.algo_config = config
        self.iteration = 0
        self._timesteps_total = 0
        self._weights_version = 0
        self.setup(self.config)

    # -- lifecycle ----------------------------------------------------
    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        self.module_spec = cfg.module_spec()
        self._offline_writer = None  # created on first logged fragment
        self.learner_group = LearnerGroup(
            learner_class=cfg.learner_class(),
            module_spec=self.module_spec, config=cfg)
        self.env_runner_group = self._build_env_runners(cfg)
        self._sync_weights()

    def _build_env_runners(self, cfg) -> "FaultTolerantActorManager | None":
        # Algorithms that recompute values/logits learner-side declare a
        # minimal column set; the runners then skip shipping the rest.
        emit = getattr(cfg, "runner_emit_columns", None)
        if cfg.num_env_runners <= 0:
            self.local_env_runner = SingleAgentEnvRunner(
                env_id=cfg.env, module_spec=self.module_spec,
                num_envs=cfg.num_envs_per_env_runner,
                rollout_fragment_length=cfg.rollout_fragment_length,
                seed=cfg.seed, worker_index=0, explore=cfg.explore,
                emit_columns=emit)
            return None
        RemoteRunner = ray_tpu.remote(SingleAgentEnvRunner)
        if getattr(cfg, "use_process_runners", False):
            RemoteRunner = RemoteRunner.options(process=True)
        runner_options = dict(getattr(cfg, "runner_actor_options", None)
                              or {})
        if runner_options:
            RemoteRunner = RemoteRunner.options(**runner_options)

        def factory(idx: int):
            return RemoteRunner.remote(
                env_id=cfg.env, module_spec=self.module_spec,
                num_envs=cfg.num_envs_per_env_runner,
                rollout_fragment_length=cfg.rollout_fragment_length,
                seed=cfg.seed, worker_index=idx + 1, explore=cfg.explore,
                emit_columns=emit)

        actors = [factory(i) for i in range(cfg.num_env_runners)]
        self.local_env_runner = None
        return FaultTolerantActorManager(actors, actor_factory=factory)

    def _sync_weights(self) -> None:
        """Broadcast learner weights to all env runners (reference:
        Algorithm's weight sync after each training_step)."""
        weights = self.learner_group.get_weights()
        self._weights_version += 1
        if self.env_runner_group is None:
            self.local_env_runner.set_weights(weights, self._weights_version)
        else:
            # Put once; every runner resolves the same object (the object
            # store is the broadcast plane, reference impala.py:676+).
            # Async + backpressured: at most one in-flight push per
            # runner, resolved pushes consumed (errors mark unhealthy).
            ref = ray_tpu.put(weights)
            self._weight_push_refs = self.env_runner_group.broadcast_async(
                "set_weights", ref, self._weights_version,
                pending=getattr(self, "_weight_push_refs", None))

    # -- Trainable protocol -------------------------------------------
    def step(self) -> dict:
        t0 = time.time()
        results = self.training_step()
        self.iteration += 1
        results.setdefault("training_iteration", self.iteration)
        results.setdefault("num_env_steps_sampled_lifetime",
                           self._timesteps_total)
        results["time_this_iter_s"] = time.time() - t0
        return results

    def train(self) -> dict:
        return self.step()

    def training_step(self) -> dict:
        raise NotImplementedError

    # -- sampling helper ----------------------------------------------
    def _sample_fragments(self) -> list[SampleBatch]:
        """One synchronous sampling round across all env runners."""
        if self.env_runner_group is None:
            sourced = [(0, self.local_env_runner.sample())]
        else:
            # Stable actor ids, NOT positional indexes: a failed runner
            # drops out of the results, and a shifted index would stitch
            # one runner's steps onto another's open episodes in the
            # offline log.
            sourced = self.env_runner_group.foreach_actor_with_ids(
                "sample")
        for _, b in sourced:
            T, B = np.shape(b["obs"])[:2]
            self._timesteps_total += T * B
        if getattr(self.algo_config, "output", None):
            if self._offline_writer is None:
                from ray_tpu.rllib.offline import OfflineWriter

                self._offline_writer = OfflineWriter(
                    self.algo_config.output,
                    self.algo_config.output_format)
            for i, b in sourced:
                self._offline_writer.write_fragment(b, source=i)
        return [b for _, b in sourced]

    def _runner_metrics(self) -> dict:
        if self.env_runner_group is None:
            metrics = [self.local_env_runner.get_metrics()]
        else:
            metrics = self.env_runner_group.foreach_actor("get_metrics")
        merged: dict = {"num_episodes": 0}
        returns = []
        for m in metrics:
            merged["num_episodes"] += m.get("num_episodes", 0)
            if "episode_return_mean" in m:
                returns.append(m["episode_return_mean"])
        if returns:
            merged["episode_return_mean"] = float(np.mean(returns))
        return merged

    # -- checkpointing (Trainable protocol) ---------------------------
    def save_checkpoint(self, checkpoint_dir: str):
        state = {
            "learner": self.learner_group.get_state(),
            "iteration": self.iteration,
            "timesteps": self._timesteps_total,
            "config": self.algo_config.to_dict(),
        }
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint) -> None:
        path = checkpoint if isinstance(checkpoint, str) else checkpoint.path
        state_file = os.path.join(path, "algorithm_state.pkl")
        with open(state_file, "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self.iteration = state["iteration"]
        self._timesteps_total = state["timesteps"]
        self._sync_weights()

    save = save_checkpoint
    restore = load_checkpoint

    def stop(self) -> None:
        self.cleanup()

    def cleanup(self) -> None:
        if getattr(self, "_offline_writer", None) is not None:
            self._offline_writer.close()
        if self.env_runner_group is not None:
            for i in self.env_runner_group.healthy_actor_ids():
                try:
                    ray_tpu.kill(self.env_runner_group.actor(i))
                except Exception:
                    pass  # runner already dead at teardown
        self.learner_group.shutdown()
