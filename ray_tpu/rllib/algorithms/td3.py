"""TD3 — Twin Delayed Deep Deterministic policy gradient.

Reference: rllib/algorithms/td3/ (config over DDPG: twin Q, delayed
policy updates, target policy smoothing — Fujimoto et al. 2018). TPU
shape: like SAC here, ONE jitted program per update kind — the critic
step and the (delayed) critic+actor+polyak step are two compiled
variants selected host-side by the step counter; no Python between the
losses inside either program.

Components:
- deterministic tanh actor with Gaussian exploration noise;
- twin Q critics with clipped double-Q targets;
- target policy smoothing: clipped noise on the TARGET action;
- delayed actor + target updates every ``policy_delay`` critic steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import (
    RLModule,
    RLModuleSpec,
    _mlp_apply,
    _mlp_init,
)
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer
from ray_tpu.rllib.utils.sample_batch import (
    Columns,
    SampleBatch,
    fragment_to_transitions,
)


class TD3Module(RLModule):
    """Deterministic tanh actor + twin Q critics."""

    def __init__(self, observation_size: int, num_actions: int = 0,
                 action_size: int = 1, hidden: tuple = (256, 256),
                 action_scale: float = 1.0, explore_noise: float = 0.1,
                 **_):
        assert num_actions == 0, "TD3 is continuous-control only"
        self.observation_size = observation_size
        self.action_size = action_size
        self.hidden = tuple(hidden)
        self.action_scale = float(action_scale)
        self.explore_noise = float(explore_noise)

    def init(self, rng):
        pi_rng, q1_rng, q2_rng = jax.random.split(rng, 3)
        obs, act, h = self.observation_size, self.action_size, self.hidden
        return {
            "pi": _mlp_init(pi_rng, (obs,) + h + (act,)),
            "q1": _mlp_init(q1_rng, (obs + act,) + h + (1,)),
            "q2": _mlp_init(q2_rng, (obs + act,) + h + (1,)),
        }

    def policy(self, params, obs):
        return jnp.tanh(_mlp_apply(params["pi"], obs)) * self.action_scale

    def q_values(self, params, obs, actions):
        x = jnp.concatenate([obs, actions], axis=-1)
        return (_mlp_apply(params["q1"], x)[..., 0],
                _mlp_apply(params["q2"], x)[..., 0])

    # -- RLModule passes ----------------------------------------------
    def forward_inference(self, params, batch, rng=None):
        a = self.policy(params, batch["obs"])
        return {"actions": a, "action_logits": a,
                "action_logp": jnp.zeros(a.shape[:-1])}

    def forward_exploration(self, params, batch, rng=None):
        a = self.policy(params, batch["obs"])
        noise = self.explore_noise * self.action_scale * \
            jax.random.normal(rng, a.shape)
        a = jnp.clip(a + noise, -self.action_scale, self.action_scale)
        return {"actions": a, "action_logits": a,
                "action_logp": jnp.zeros(a.shape[:-1]),
                "vf_preds": jnp.zeros(a.shape[:-1])}

    def forward_train(self, params, batch, rng=None):
        return {}


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "Pendulum-v1"
        self.module_class = TD3Module
        self.model_config = {"hidden": (256, 256)}
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.tau = 0.005
        self.policy_delay = 2            # critic steps per actor step
        self.target_noise = 0.2          # target policy smoothing sigma
        self.target_noise_clip = 0.5
        self.explore_noise = 0.1
        self.buffer_capacity = 100_000
        self.train_batch_size = 256
        self.num_steps_sampled_before_learning = 1500
        self.updates_per_iteration = 64

    def module_spec(self):
        spec = super().module_spec()
        spec.model_config.setdefault("explore_noise", self.explore_noise)
        return spec

    def learner_class(self):
        return TD3Learner


class TD3Learner(Learner):
    """Two compiled update variants: critic-only and
    critic+actor+polyak (the delayed step). The host picks by step
    counter (reference: td3 policy_delay)."""

    def __init__(self, module_spec: RLModuleSpec, config=None, mesh=None):
        super().__init__(module_spec, config, mesh)
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        # The actor gets its OWN optimizer, touched only on delayed
        # steps: routing zero grads through a shared Adam would still
        # move the policy via leftover momentum on every critic step,
        # violating policy_delay (the reference's separate optimizers
        # have the same effect).
        self._actor_opt = optax.adam(
            getattr(config, "actor_lr", 1e-3) if config else 1e-3)
        self._actor_opt_state = self._actor_opt.init(self.params["pi"])
        self.opt_state = self.optimizer.init(
            {"q1": self.params["q1"], "q2": self.params["q2"]})
        self._updates = {}  # do_actor -> jitted fn

    def configure_optimizer(self):
        # Critic optimizer only (over {q1, q2}); see __init__ for the
        # actor's dedicated transform.
        return optax.adam(getattr(self.config, "critic_lr", 1e-3)
                          if self.config else 1e-3)

    def _build_update(self, do_actor: bool):
        cfg = self.config
        gamma = cfg.gamma
        tau = getattr(cfg, "tau", 0.005)
        target_noise = float(getattr(cfg, "target_noise", 0.2))
        noise_clip = float(getattr(cfg, "target_noise_clip", 0.5))
        module = self.module
        scale = module.action_scale

        def update(params, opt_state, actor_opt_state, target_params,
                   batch, rng):
            # --- critic: clipped double-Q with SMOOTHED target action
            next_a = module.policy(target_params, batch[Columns.NEXT_OBS])
            smoothing = jnp.clip(
                target_noise * scale * jax.random.normal(
                    rng, next_a.shape),
                -noise_clip * scale, noise_clip * scale)
            next_a = jnp.clip(next_a + smoothing, -scale, scale)
            tq1, tq2 = module.q_values(
                target_params, batch[Columns.NEXT_OBS], next_a)
            not_done = 1.0 - batch[Columns.TERMINATEDS].astype(jnp.float32)
            targets = jax.lax.stop_gradient(
                batch[Columns.REWARDS]
                + gamma * not_done * jnp.minimum(tq1, tq2))

            def critic_loss_fn(p):
                q1, q2 = module.q_values(
                    p, batch[Columns.OBS], batch[Columns.ACTIONS])
                return 0.5 * (jnp.mean(jnp.square(q1 - targets))
                              + jnp.mean(jnp.square(q2 - targets))), q1

            (critic_loss, q1_vals), critic_grads = jax.value_and_grad(
                critic_loss_fn, has_aux=True)(params)
            critic_only = {"q1": critic_grads["q1"],
                           "q2": critic_grads["q2"]}
            updates, opt_state = self.optimizer.update(
                critic_only, opt_state,
                {"q1": params["q1"], "q2": params["q2"]})
            new_critics = optax.apply_updates(
                {"q1": params["q1"], "q2": params["q2"]}, updates)
            params = {**params, **new_critics}
            actor_loss = jnp.zeros(())
            if do_actor:
                def actor_loss_fn(pi):
                    p = {**params, "pi": pi}
                    a = module.policy(p, batch[Columns.OBS])
                    q1, _ = module.q_values(p, batch[Columns.OBS], a)
                    return -jnp.mean(q1)

                actor_loss, pi_grads = jax.value_and_grad(
                    actor_loss_fn)(params["pi"])
                pi_updates, actor_opt_state = self._actor_opt.update(
                    pi_grads, actor_opt_state, params["pi"])
                params = {**params, "pi": optax.apply_updates(
                    params["pi"], pi_updates)}
                target_params = jax.tree_util.tree_map(
                    lambda t, o: (1 - tau) * t + tau * o,
                    target_params, params)
            metrics = {"critic_loss": critic_loss,
                       "actor_loss": actor_loss,
                       "q_mean": jnp.mean(q1_vals)}
            return (params, opt_state, actor_opt_state, target_params,
                    metrics)

        return jax.jit(update)

    def update_from_batch(self, batch: SampleBatch,
                          sync_metrics: bool = True) -> dict:
        delay = max(1, int(getattr(self.config, "policy_delay", 2)))
        do_actor = (self._steps + 1) % delay == 0
        fn = self._updates.get(do_actor)
        if fn is None:
            fn = self._updates[do_actor] = self._build_update(do_actor)
        self._rng, rng = jax.random.split(self._rng)
        arrays = self._device_batch(batch)
        (self.params, self.opt_state, self._actor_opt_state,
         self.target_params, metrics) = fn(
            self.params, self.opt_state, self._actor_opt_state,
            self.target_params, arrays, rng)
        self._steps += 1
        if not sync_metrics:
            return metrics  # device arrays; caller syncs when it reports
        host = jax.device_get(metrics)  # one transfer for all scalars
        return {k: float(v) for k, v in host.items()}

    def get_state(self) -> dict:
        state = super().get_state()
        state["target_params"] = jax.device_get(self.target_params)
        state["actor_opt_state"] = jax.device_get(self._actor_opt_state)
        return state

    def set_state(self, state: dict) -> None:
        super().set_state(state)
        if "target_params" in state:
            self.target_params = state["target_params"]
        if "actor_opt_state" in state:
            self._actor_opt_state = state["actor_opt_state"]


class TD3(Algorithm):
    """Off-policy loop: replay buffer of flat transitions, N jitted
    updates per iteration (same skeleton as SAC/DQN)."""

    config_class = TD3Config

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        if cfg.num_learners > 0:
            raise ValueError(
                "TD3 runs on a local learner (one jitted program per "
                "update); scale over devices with "
                "num_devices_per_learner instead of num_learners")
        super().setup(config)
        self.replay = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self._learner_steps = 0

    def training_step(self) -> dict:
        cfg = self.algo_config
        for frag in self._sample_fragments():
            self.replay.add(fragment_to_transitions(frag))
        metrics: dict = {}
        if len(self.replay) >= cfg.num_steps_sampled_before_learning:
            for _ in range(cfg.updates_per_iteration):
                batch = self.replay.sample(cfg.train_batch_size)
                metrics = self.learner_group.update_from_batch(batch)
                self._learner_steps += 1
            self._sync_weights()
        results = self._runner_metrics()
        results.update(metrics)
        results["replay_buffer_size"] = len(self.replay)
        results["num_learner_steps"] = self._learner_steps
        return results


TD3Config.algo_class = TD3


class DDPGConfig(TD3Config):
    """DDPG (reference: rllib/algorithms/ddpg/) as the TD3 ancestor it
    is: no policy delay, no target-action smoothing — a single
    deterministic actor-critic update per step. The twin critic stays
    (strictly an upgrade over classic DDPG's single critic; the
    reference's DDPG gained the same option)."""

    def __init__(self):
        super().__init__()
        self.policy_delay = 1
        self.target_noise = 0.0
        self.target_noise_clip = 0.0


class DDPG(TD3):
    config_class = DDPGConfig


DDPGConfig.algo_class = DDPG
