"""CRR — Critic-Regularized Regression (offline RL, discrete actions).

Reference: rllib/algorithms/crr/ (Wang et al. 2020). Offline policy
learning where behavior cloning is filtered through a learned critic:

- the critic Q(s, a) trains by expected-SARSA TD against a target
  network, with the expectation over the CURRENT policy's action
  distribution (no max — stays in-distribution on offline data);
- the policy trains by advantage-weighted log-likelihood:
  weight = 1[A(s,a) > 0]  ("binary", the paper's best-performing form)
  or exp(A(s,a) / beta) clipped  ("exp"),
  where A(s,a) = Q(s,a) - E_{a'~pi}[Q(s,a')].

Both heads update in ONE jitted program; the offline input rides the
same row format as BC/MARWIL/CQL (algorithm.load_offline_rows), with
next_obs required for the TD target.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm,
    load_offline_rows,
)
from ray_tpu.rllib.algorithms.bc import MARWIL, MARWILConfig
from ray_tpu.rllib.core.learner import TargetNetworkLearner
from ray_tpu.rllib.core.rl_module import (
    RLModule,
    _mlp_apply,
    _mlp_init,
    categorical_logp,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class CRRModule(RLModule):
    """Separate policy and Q networks over a shared MLP recipe."""

    def __init__(self, observation_size: int, num_actions: int,
                 hidden: tuple = (64, 64), **_):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, rng):
        pi_rng, q_rng = jax.random.split(rng)
        sizes = ((self.observation_size,) + self.hidden
                 + (self.num_actions,))
        return {"pi": _mlp_init(pi_rng, sizes),
                "q": _mlp_init(q_rng, sizes)}

    def q_values(self, params, obs):
        return _mlp_apply(params["q"], obs)

    def _logits(self, params, obs):
        return _mlp_apply(params["pi"], obs)

    def forward_inference(self, params, batch, rng=None):
        logits = self._logits(params, batch["obs"])
        return {"action_logits": logits,
                "actions": jnp.argmax(logits, axis=-1)}

    def forward_exploration(self, params, batch, rng=None):
        logits = self._logits(params, batch["obs"])
        actions = jax.random.categorical(rng, logits)
        return {"action_logits": logits, "actions": actions,
                "action_logp": categorical_logp(logits, actions),
                "vf_preds": jnp.zeros_like(logits[..., 0])}

    def forward_train(self, params, batch, rng=None):
        return {"action_logits": self._logits(params, batch["obs"]),
                "q_values": self.q_values(params, batch["obs"])}


class CRRConfig(MARWILConfig):
    """Inherits MARWIL's offline plumbing (input_, offline_data(),
    evaluation()); swaps in the critic-regularized module/learner."""

    def __init__(self):
        super().__init__()
        self.module_class = CRRModule
        self.lr = 1e-3
        self.weight_type = "bin"      # "bin" | "exp"
        self.temperature = 1.0        # beta for the "exp" weight
        self.max_weight = 20.0        # exp-weight clip (paper's CWP cap)
        self.critic_loss_coeff = 1.0
        self.target_update_freq = 100
        self.train_batch_size = 256
        self.updates_per_iteration = 64

    def learner_class(self):
        return CRRLearner


class CRRLearner(TargetNetworkLearner):
    def compute_loss(self, params, batch, rng):
        cfg = self.config
        out = self.module.forward_train(
            params, {"obs": batch[Columns.OBS]}, rng)
        logits, q = out["action_logits"], out["q_values"]
        actions = batch[Columns.ACTIONS].astype(jnp.int32)
        q_taken = jnp.take_along_axis(
            q, actions[..., None], axis=-1)[..., 0]

        # Critic: expected SARSA against the target net, expectation
        # under the current policy at s' (kept in-distribution).
        next_logits = self.module._logits(params, batch[Columns.NEXT_OBS])
        next_pi = jax.nn.softmax(
            jax.lax.stop_gradient(next_logits), axis=-1)
        q_next = self.module.q_values(
            batch["target_params"], batch[Columns.NEXT_OBS])
        v_next = jnp.sum(next_pi * q_next, axis=-1)
        not_done = 1.0 - batch[Columns.TERMINATEDS].astype(jnp.float32)
        targets = batch[Columns.REWARDS] + cfg.gamma * not_done * v_next
        critic_loss = jnp.mean(jnp.square(
            q_taken - jax.lax.stop_gradient(targets)))

        # Policy: advantage-filtered behavior cloning.
        pi = jax.nn.softmax(logits, axis=-1)
        v = jnp.sum(jax.lax.stop_gradient(pi) * q, axis=-1)
        adv = jax.lax.stop_gradient(q_taken - v)
        if cfg.weight_type == "exp":
            weights = jnp.minimum(
                jnp.exp(adv / cfg.temperature), cfg.max_weight)
        else:
            weights = (adv > 0).astype(jnp.float32)
        logp = categorical_logp(logits, actions)
        policy_loss = -jnp.mean(weights * logp)

        total = policy_loss + cfg.critic_loss_coeff * critic_loss
        return total, {"policy_loss": policy_loss,
                       "critic_loss": critic_loss,
                       "mean_advantage_weight": jnp.mean(weights),
                       "q_mean": jnp.mean(q_taken)}

def _rows_to_transitions(rows: list[dict]) -> SampleBatch:
    """Offline rows -> (s, a, r, s', done); rows missing next_obs are
    reconstructed from episode order (next row's obs), dropping each
    episode's final row when it terminated without a successor."""
    have_next = all(("next_obs" in r or "new_obs" in r) for r in rows)
    obs, actions, rewards, next_obs, dones = [], [], [], [], []
    for i, r in enumerate(rows):
        done = bool(r.get("terminateds", False)
                    or r.get("truncateds", False))
        if have_next:
            nxt = r.get("next_obs", r.get("new_obs"))
        elif not done and i + 1 < len(rows):
            nxt = rows[i + 1]["obs"]
        elif r.get("terminateds", False):
            nxt = r["obs"]  # terminal: masked out by the done flag
        else:
            # Truncated (or trailing) without a successor: the target
            # would need v(s_true_next), which the log doesn't have.
            continue
        obs.append(r["obs"])
        actions.append(r["actions"])
        rewards.append(float(r.get("rewards", 0.0)))
        next_obs.append(nxt)
        dones.append(bool(r.get("terminateds", False)))
    return SampleBatch({
        Columns.OBS: np.asarray(obs, dtype=np.float32),
        Columns.ACTIONS: np.asarray(actions),
        Columns.REWARDS: np.asarray(rewards, dtype=np.float32),
        Columns.NEXT_OBS: np.asarray(next_obs, dtype=np.float32),
        Columns.TERMINATEDS: np.asarray(dones),
    })


class CRR(MARWIL):
    """Reuses MARWIL's offline loop/eval scaffolding with the
    critic-regularized update and transition-format batches."""

    config_class = CRRConfig

    def setup(self, config: dict) -> None:
        Algorithm.setup(self, config)
        cfg = self.algo_config
        self._train_batch = _rows_to_transitions(
            load_offline_rows(cfg.input_))
        if len(self._train_batch) == 0:
            raise ValueError("CRR: offline input produced no transitions")
        self._rng = np.random.default_rng(cfg.seed)
        self._learner_steps = 0


CRRConfig.algo_class = CRR
