"""IMPALA — async actor-learner architecture with V-trace.

Reference: rllib/algorithms/impala/impala.py:552/:667 (training_step:
async sampling, batches shipped as object refs :676-698, central
learner consuming a queue, periodic weight pushes).

TPU shape: env-runner actors sample continuously with a bounded
in-flight request pool (FaultTolerantActorManager.submit); fragments
flow through the object store; the learner runs ONE jitted update per
train batch with the V-trace off-policy correction computed as a
reverse `lax.scan` on device (replaces the reference's numpy/torch
vtrace in impala/vtrace_torch.py).
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import (
    categorical_entropy,
    categorical_logp,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


def vtrace(behavior_logp, target_logp, rewards, values, bootstrap_value,
           terminateds, truncateds, gamma, clip_rho_threshold=1.0,
           clip_c_threshold=1.0):
    """V-trace targets (Espeholt et al. 2018) over a [T, B] fragment.

    Pure-JAX reverse scan; everything stays on device inside the jitted
    learner update.

    Episode boundaries inside the fragment: termination cuts the return
    to the immediate reward; truncation bootstraps from the value
    function, approximating v(s_true_next) with the stored v(s_t) (the
    auto-reset next row belongs to a NEW episode — same convention as
    compute_gae in core/learner.py).
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
    cs = jnp.minimum(clip_c_threshold, rhos)
    not_term = 1.0 - terminateds.astype(jnp.float32)
    boundary = jnp.logical_or(terminateds, truncateds)
    cont = 1.0 - boundary.astype(jnp.float32)

    next_values = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    next_values = jnp.where(truncateds, values, next_values)
    deltas = clipped_rhos * (
        rewards + gamma * not_term * next_values - values)

    def scan_fn(acc, xs):
        delta, c, ct = xs
        acc = delta + gamma * ct * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap_value),
        (deltas, cs, cont), reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    next_vs = jnp.where(truncateds, values, next_vs)
    pg_advantages = clipped_rhos * (
        rewards + gamma * not_term * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_advantages)


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_c_threshold = 1.0
        self.num_batches_per_step = 4
        self.max_requests_in_flight_per_env_runner = 2
        self.broadcast_interval = 1  # learner steps between weight pushes
        self.lr = 5e-4
        # The V-trace learner recomputes logits/values under grad; the
        # runners only need to ship the behavior log-probs (cuts batch
        # transport by ~a third).
        self.runner_emit_columns = (Columns.ACTION_LOGP,)

    def learner_class(self):
        return IMPALALearner


class IMPALALearner(Learner):
    """V-trace actor-critic loss (reference:
    impala/torch/impala_torch_learner.py). Consumes TIME-MAJOR [T, B]
    batches — no flattening before the loss; the scan wants [T, B].
    The data axis for mesh sharding is therefore axis 1 (env lanes),
    keeping the time scan local to each device."""

    batch_axis = 1

    def compute_loss(self, params, batch, rng):
        cfg = self.config
        T, B = batch[Columns.REWARDS].shape
        flat = {"obs": batch[Columns.OBS].reshape(
            (T * B,) + batch[Columns.OBS].shape[2:])}
        out = self.module.forward_train(params, flat, rng)
        logits = out["action_logits"].reshape(T, B, -1)
        values = out["vf_preds"].reshape(T, B)

        target_logp = categorical_logp(logits, batch[Columns.ACTIONS])
        vs, pg_adv = vtrace(
            batch[Columns.ACTION_LOGP], target_logp,
            batch[Columns.REWARDS], values, batch["bootstrap_value"],
            batch[Columns.TERMINATEDS], batch[Columns.TRUNCATEDS],
            cfg.gamma, cfg.clip_rho_threshold, cfg.clip_c_threshold)

        pg_loss = -jnp.mean(target_logp * pg_adv)
        vf_loss = 0.5 * jnp.mean(jnp.square(values - vs))
        entropy = jnp.mean(categorical_entropy(logits))
        total = (pg_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}



class IMPALA(Algorithm):
    config_class = IMPALAConfig

    def setup(self, config: dict) -> None:
        super().setup(config)
        self._pending: list = []          # (actor_id, ref) in flight
        self._batch_queue: collections.deque = collections.deque(maxlen=16)
        self._learner_steps = 0

    def _pump_sampling(self) -> None:
        """Keep every env runner saturated with sample() requests
        (shared bounded in-flight pump: actor_manager.pump)."""
        group = self.env_runner_group
        if group is None:
            self._batch_queue.append(self.local_env_runner.sample())
            return
        self._pending = group.pump(
            "sample", self._pending, self._batch_queue.append)

    def training_step(self) -> dict:
        cfg = self.algo_config
        metrics: dict = {}
        trained = 0
        batches_this_step = 0

        while batches_this_step < cfg.num_batches_per_step:
            group = self.env_runner_group
            if group is not None and group.num_healthy_actors() == 0:
                # All runners dead: try factory-based recovery before
                # giving up — never spin forever on an empty queue.
                if not group.probe_unhealthy_actors():
                    raise RuntimeError(
                        "IMPALA: all env-runner actors are unhealthy and "
                        "could not be restarted")
                self._sync_weights()
            self._pump_sampling()
            while self._batch_queue and (
                    batches_this_step < cfg.num_batches_per_step):
                batch = self._batch_queue.popleft()
                T, B = np.shape(batch[Columns.REWARDS])
                self._timesteps_total += T * B
                sb = SampleBatch({
                    k: batch[k] for k in (
                        Columns.OBS, Columns.ACTIONS, Columns.REWARDS,
                        Columns.TERMINATEDS, Columns.TRUNCATEDS,
                        Columns.ACTION_LOGP)})
                sb["bootstrap_value"] = batch["bootstrap_value"]
                # Lazy metrics: no device sync inside the hot loop.
                metrics = self.learner_group.update_from_batch(
                    sb, shard=False, sync_metrics=False)
                trained += T * B
                self._learner_steps += 1
                batches_this_step += 1
                if self._learner_steps % cfg.broadcast_interval == 0:
                    self._sync_weights()

        results = self._runner_metrics()
        if metrics:
            # One device->host sync per training_step, not per update.
            host = jax.device_get(metrics)
            results.update({k: float(v) for k, v in host.items()})
        results["num_env_steps_trained"] = trained
        results["num_learner_steps"] = self._learner_steps
        return results


IMPALAConfig.algo_class = IMPALA
