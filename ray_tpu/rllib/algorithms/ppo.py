"""PPO — Proximal Policy Optimization (new-API-stack shape).

Reference: rllib/algorithms/ppo/ppo.py:379/:405/:414 (training_step:
parallel EnvRunner.sample -> learner_group.update) and
ppo/torch/ppo_torch_learner.py (clipped-surrogate loss). The loss,
GAE, and minibatch epochs here are pure JAX: GAE is a reverse
`lax.scan` (core/learner.py:compute_gae) and each SGD minibatch is one
jitted update on static shapes.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner, compute_gae
from ray_tpu.rllib.core.rl_module import (
    categorical_entropy,
    categorical_kl,
    categorical_logp,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch

import jax.numpy as jnp


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lambda_ = 0.95
        self.clip_param = 0.3
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        self.num_epochs = 4
        self.minibatch_size = 128

    def learner_class(self):
        return PPOLearner


class PPOLearner(Learner):
    """Clipped-surrogate loss (reference: ppo_torch_learner.py
    compute_loss_for_module)."""

    def compute_loss(self, params, batch, rng):
        cfg = self.config
        out = self.module.forward_train(params, batch, rng)
        logits = out["action_logits"]
        values = out["vf_preds"]

        logp = categorical_logp(logits, batch[Columns.ACTIONS])
        ratio = jnp.exp(logp - batch[Columns.ACTION_LOGP])
        advantages = batch[Columns.ADVANTAGES]

        surrogate = jnp.minimum(
            advantages * ratio,
            advantages * jnp.clip(ratio, 1 - cfg.clip_param,
                                  1 + cfg.clip_param))

        vf_targets = batch[Columns.VALUE_TARGETS]
        vf_err = jnp.square(values - vf_targets)
        vf_loss = jnp.clip(vf_err, 0, cfg.vf_clip_param)

        entropy = categorical_entropy(logits)
        kl = categorical_kl(batch[Columns.ACTION_LOGITS], logits)

        total = jnp.mean(
            -surrogate
            + cfg.vf_loss_coeff * vf_loss
            - cfg.entropy_coeff * entropy
            + cfg.kl_coeff * kl)
        metrics = {
            "policy_loss": -jnp.mean(surrogate),
            "vf_loss": jnp.mean(vf_loss),
            "entropy": jnp.mean(entropy),
            "mean_kl": jnp.mean(kl),
        }
        return total, metrics


def postprocess_fragment(batch: SampleBatch, gamma: float,
                         lam: float) -> SampleBatch:
    """GAE over a time-major [T, B] fragment, then flatten to [T*B].

    Runs as one jitted scan on device; the flattened batch is what the
    minibatch SGD loop consumes.
    """
    advantages, value_targets = compute_gae(
        jnp.asarray(batch[Columns.REWARDS]),
        jnp.asarray(batch[Columns.VF_PREDS]),
        jnp.asarray(batch["bootstrap_value"]),
        jnp.asarray(batch[Columns.TERMINATEDS]),
        jnp.asarray(batch[Columns.TRUNCATEDS]),
        gamma, lam)
    adv = np.asarray(advantages)
    flat = SampleBatch()
    for key in (Columns.OBS, Columns.ACTIONS, Columns.ACTION_LOGP,
                Columns.ACTION_LOGITS, Columns.VF_PREDS):
        v = np.asarray(batch[key])
        flat[key] = v.reshape((-1,) + v.shape[2:])
    flat[Columns.ADVANTAGES] = adv.reshape(-1)
    flat[Columns.VALUE_TARGETS] = np.asarray(value_targets).reshape(-1)
    # Advantage normalization (standard PPO practice; reference does this
    # per-minibatch in the learner connector).
    a = flat[Columns.ADVANTAGES]
    flat[Columns.ADVANTAGES] = (a - a.mean()) / (a.std() + 1e-8)
    return flat


class PPO(Algorithm):
    config_class = PPOConfig

    def training_step(self) -> dict:
        cfg = self.algo_config
        fragments = self._sample_fragments()
        train_batch = SampleBatch.concat(
            [postprocess_fragment(f, cfg.gamma, cfg.lambda_)
             for f in fragments])

        rng = np.random.default_rng(cfg.seed + self.iteration)
        metrics: dict = {}
        num_updates = 0
        mb = min(cfg.minibatch_size, len(train_batch))
        for _ in range(cfg.num_epochs):
            for minibatch in train_batch.minibatches(mb, rng):
                metrics = self.learner_group.update_from_batch(minibatch)
                num_updates += 1
        self._sync_weights()

        results = self._runner_metrics()
        results.update(metrics)
        results["num_sgd_updates"] = num_updates
        results["num_env_steps_trained"] = len(train_batch)
        return results


PPOConfig.algo_class = PPO
