"""DreamerV3 — model-based RL: learn a latent world model, train the
policy inside it.

Reference behavior: rllib/algorithms/dreamerv3/dreamerv3.py:469 (the
training_step: replay-sample -> world-model update -> imagination ->
actor/critic update) and the DreamerV3 paper's components (RSSM with
categorical latents, KL balancing + free bits, symlog heads, lambda-
return actor-critic on imagined trajectories). Redesigned TPU-first:
the whole update — world model BPTT over the sequence, H-step
imagination via lax.scan, actor/critic losses — is ONE jitted program,
so on a TPU chip the entire Dreamer step is a single XLA execution
with no host round-trips between the three optimizers.

Scaled for vector-obs toy envs (CartPole-scale): MLP encoder/decoder,
small RSSM; the architecture (not the sizes) is the paper's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env.vector_env import make_vector_env

# --------------------------------------------------------------------------
# Config
# --------------------------------------------------------------------------


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 4e-4
        self.actor_lr = 1e-4
        self.critic_lr = 1e-4
        self.deter_size = 128        # GRU state
        self.stoch_groups = 8        # categorical groups
        self.stoch_classes = 8       # classes per group
        self.units = 128             # MLP width
        self.seq_len = 16            # world-model BPTT length
        self.batch_sequences = 16    # sequences per update
        self.imagine_horizon = 10
        self.replay_capacity = 100_000
        self.prefill_steps = 500
        self.env_steps_per_update = 64   # real steps between updates
        self.updates_per_iteration = 10
        self.free_nats = 1.0
        self.kl_dyn_scale = 0.5
        self.kl_rep_scale = 0.1
        self.gamma = 0.997
        self.lambda_ = 0.95
        self.entropy_coeff = 3e-3
        self.critic_ema = 0.98
        self.num_envs = 8


# --------------------------------------------------------------------------
# Model pieces (pure functions over param pytrees)
# --------------------------------------------------------------------------


def _mlp_init(key, sizes):
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / fan_in)
        params.append({
            "w": jax.random.normal(sub, (fan_in, fan_out)) * scale,
            "b": jnp.zeros((fan_out,)),
        })
    return params


def _mlp(params, x, final_linear=True):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i + 1 < len(params) or not final_linear:
            x = jax.nn.silu(x)
    return x


def _gru_init(key, in_size, hidden):
    k1, k2 = jax.random.split(key)
    scale = jnp.sqrt(1.0 / (in_size + hidden))
    return {
        "wi": jax.random.normal(k1, (in_size, 3 * hidden)) * scale,
        "wh": jax.random.normal(k2, (hidden, 3 * hidden)) * scale,
        "b": jnp.zeros((3 * hidden,)),
    }


def _gru(params, h, x):
    gates = x @ params["wi"] + h @ params["wh"] + params["b"]
    r, z, n = jnp.split(gates, 3, axis=-1)
    r, z = jax.nn.sigmoid(r), jax.nn.sigmoid(z)
    n = jnp.tanh(r * n)
    return (1 - z) * n + z * h


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _sample_categorical(key, logits):
    """Straight-through one-hot sample over [.., G, C] logits with 1%
    uniform mixing (the paper's unimix, keeps gradients alive)."""
    probs = 0.99 * jax.nn.softmax(logits) + 0.01 / logits.shape[-1]
    idx = jax.random.categorical(key, jnp.log(probs))
    one_hot = jax.nn.one_hot(idx, logits.shape[-1])
    return one_hot + probs - jax.lax.stop_gradient(probs)


def _kl_cat(logits_p, logits_q):
    """KL(p || q) over the categorical groups, summed across groups."""
    p = 0.99 * jax.nn.softmax(logits_p) + 0.01 / logits_p.shape[-1]
    q = 0.99 * jax.nn.softmax(logits_q) + 0.01 / logits_q.shape[-1]
    return jnp.sum(p * (jnp.log(p) - jnp.log(q)), axis=(-2, -1))


class DreamerV3(Algorithm):
    """Self-contained model-based algorithm: owns its replay buffer,
    vector env, and three optimizers (world model / actor / critic)."""

    config_class = DreamerV3Config

    # ------------------------------------------------------------- setup

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        self.learner_group = None
        self.env_runner_group = None
        self.local_env_runner = None
        self._offline_writer = None
        self.env = make_vector_env(cfg.env, cfg.num_envs)
        if not self.env.num_actions:
            raise ValueError("DreamerV3 here supports discrete actions")
        self._obs_size = self.env.observation_size
        self._n_act = self.env.num_actions
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._np_rng = np.random.default_rng(cfg.seed)
        self.params = self._init_params()
        self._wm_opt = optax.adam(cfg.lr)
        self._actor_opt = optax.adam(cfg.actor_lr)
        self._critic_opt = optax.adam(cfg.critic_lr)
        self._opt_state = {
            "wm": self._wm_opt.init(self.params["wm"]),
            "actor": self._actor_opt.init(self.params["actor"]),
            "critic": self._critic_opt.init(self.params["critic"]),
        }
        self.params["critic_ema"] = jax.tree.map(
            lambda x: x, self.params["critic"])
        self._replay = _SequenceReplay(
            cfg.replay_capacity, cfg.num_envs, self._obs_size)
        self._obs = self.env.reset(seed=cfg.seed)
        # Per-lane live RSSM state (+ previous action) for acting in
        # the REAL env.
        self._act_state = self._initial_act_state(cfg.num_envs)
        self._update_fn = jax.jit(self._build_update())
        self._policy_fn = jax.jit(self._build_policy())
        self._episode_returns: list[float] = []
        self._lane_return = np.zeros(cfg.num_envs, dtype=np.float64)

    def _init_params(self) -> dict:
        cfg = self.algo_config
        key = self._rng
        keys = jax.random.split(key, 10)
        z_size = cfg.stoch_groups * cfg.stoch_classes
        feat = cfg.deter_size + z_size
        u = cfg.units
        return {
            "wm": {
                "encoder": _mlp_init(keys[0],
                                     [self._obs_size, u, u]),
                "gru": _gru_init(keys[1], z_size + self._n_act,
                                 cfg.deter_size),
                "prior": _mlp_init(keys[2], [cfg.deter_size, u, z_size]),
                "post": _mlp_init(keys[3], [cfg.deter_size + u, u,
                                            z_size]),
                "decoder": _mlp_init(keys[4], [feat, u, self._obs_size]),
                "reward": _mlp_init(keys[5], [feat, u, 1]),
                "cont": _mlp_init(keys[6], [feat, u, 1]),
            },
            "actor": _mlp_init(keys[7], [feat, u, self._n_act]),
            "critic": _mlp_init(keys[8], [feat, u, 1]),
        }

    def _initial_state(self, batch: int):
        cfg = self.algo_config
        return (jnp.zeros((batch, cfg.deter_size)),
                jnp.zeros((batch,
                           cfg.stoch_groups * cfg.stoch_classes)))

    def _initial_act_state(self, batch: int):
        h, z = self._initial_state(batch)
        return (h, z, jnp.zeros((batch, self._n_act)))

    # ------------------------------------------------ jitted programs

    def _obs_step(self, wm, h, z, action_onehot, embed, key):
        """One posterior RSSM step: (h,z,a) + embed -> (h', z')."""
        cfg = self.algo_config
        h = _gru(wm["gru"], h, jnp.concatenate(
            [z, action_onehot], axis=-1))
        post_logits = _mlp(wm["post"], jnp.concatenate(
            [h, embed], axis=-1)).reshape(
                h.shape[0], cfg.stoch_groups, cfg.stoch_classes)
        z = _sample_categorical(key, post_logits).reshape(
            h.shape[0], -1)
        return h, z, post_logits

    def _img_step(self, wm, h, z, action_onehot, key):
        """One prior (imagination) step."""
        cfg = self.algo_config
        h = _gru(wm["gru"], h, jnp.concatenate(
            [z, action_onehot], axis=-1))
        prior_logits = _mlp(wm["prior"], h).reshape(
            h.shape[0], cfg.stoch_groups, cfg.stoch_classes)
        z = _sample_categorical(key, prior_logits).reshape(
            h.shape[0], -1)
        return h, z

    def _build_policy(self):
        def policy(params, state, obs, key):
            """state = (h, z, a_prev): fold the CURRENT observation
            into the posterior first, then act from it — training
            feeds the actor feats whose z is the posterior of the
            current step's observation, and acting must match (a
            one-step-stale latent visibly degrades reactive envs)."""
            wm = params["wm"]
            h, z, a_prev = state
            embed = _mlp(wm["encoder"], symlog(obs))
            k1, k2 = jax.random.split(key)
            h, z, _ = self._obs_step(wm, h, z, a_prev, embed, k2)
            feat = jnp.concatenate([h, z], axis=-1)
            logits = _mlp(params["actor"], feat)
            action = jax.random.categorical(k1, logits)
            a_onehot = jax.nn.one_hot(action, self._n_act)
            return action, (h, z, a_onehot)

        return policy

    def _build_update(self):
        cfg = self.algo_config
        G, C = cfg.stoch_groups, cfg.stoch_classes

        def world_model_loss(wm, batch, key):
            obs = symlog(batch["obs"])              # [B, L, obs]
            B, L = obs.shape[:2]
            embed = _mlp(wm["encoder"], obs)        # [B, L, u]
            a_onehot = jax.nn.one_hot(batch["actions"], self._n_act)
            h, z = self._initial_state(B)

            def step(carry, xs):
                h, z, key = carry
                emb_t, a_prev, reset_t = xs
                # Episode boundary inside the sequence: restart the
                # latent AND a_prev (the policy acts with a_prev=0 at
                # every episode start; training must see the same
                # (0, 0, 0) input or the model never learns it).
                h = h * (1.0 - reset_t)[:, None]
                z = z * (1.0 - reset_t)[:, None]
                a_prev = a_prev * (1.0 - reset_t)[:, None]
                key, sub = jax.random.split(key)
                h2 = _gru(wm["gru"], h, jnp.concatenate(
                    [z, a_prev], axis=-1))
                prior_logits = _mlp(wm["prior"], h2).reshape(B, G, C)
                post_logits = _mlp(wm["post"], jnp.concatenate(
                    [h2, emb_t], axis=-1)).reshape(B, G, C)
                z2 = _sample_categorical(sub, post_logits).reshape(B, -1)
                return (h2, z2, key), (h2, z2, prior_logits, post_logits)

            # a_prev[t] = action taken BEFORE obs[t] arrived.
            a_prev = jnp.concatenate(
                [jnp.zeros_like(a_onehot[:, :1]), a_onehot[:, :-1]],
                axis=1)
            resets = jnp.concatenate(
                [jnp.zeros_like(batch["dones"][:, :1]),
                 batch["dones"][:, :-1]], axis=1)
            (_, _, _), (hs, zs, priors, posts) = jax.lax.scan(
                step, (h, z, key),
                (embed.transpose(1, 0, 2),
                 a_prev.transpose(1, 0, 2),
                 resets.transpose(1, 0)))
            hs = hs.transpose(1, 0, 2)              # [B, L, deter]
            zs = zs.transpose(1, 0, 2)              # [B, L, z]
            priors = priors.transpose(1, 0, 2, 3)
            posts = posts.transpose(1, 0, 2, 3)
            feat = jnp.concatenate([hs, zs], axis=-1)

            recon = _mlp(wm["decoder"], feat)
            recon_loss = jnp.mean(jnp.sum(
                jnp.square(recon - obs), axis=-1))
            rew_pred = _mlp(wm["reward"], feat)[..., 0]
            reward_loss = jnp.mean(jnp.square(
                rew_pred - symlog(batch["rewards"])))
            cont_pred = _mlp(wm["cont"], feat)[..., 0]
            cont_target = 1.0 - batch["terminateds"]
            cont_loss = jnp.mean(
                optax.sigmoid_binary_cross_entropy(cont_pred,
                                                   cont_target))
            # KL balancing with free bits (per the paper).
            dyn = jnp.maximum(cfg.free_nats, jnp.mean(_kl_cat(
                jax.lax.stop_gradient(posts), priors)))
            rep = jnp.maximum(cfg.free_nats, jnp.mean(_kl_cat(
                posts, jax.lax.stop_gradient(priors))))
            loss = (recon_loss + reward_loss + cont_loss
                    + cfg.kl_dyn_scale * dyn + cfg.kl_rep_scale * rep)
            metrics = {"wm_loss": loss, "recon_loss": recon_loss,
                       "reward_loss": reward_loss, "kl_dyn": dyn}
            return loss, (feat, metrics)

        def imagine(params, feat0, key):
            """Roll the PRIOR H steps from real posterior states using
            the actor; returns imagined feats/actions/logits."""
            cfg_h = cfg.imagine_horizon
            wm = params["wm"]
            deter = cfg.deter_size
            h = feat0[:, :deter]
            z = feat0[:, deter:]

            def step(carry, key):
                h, z = carry
                feat = jnp.concatenate([h, z], axis=-1)
                logits = _mlp(params["actor"], feat)
                k1, k2 = jax.random.split(key)
                action = jax.random.categorical(k1, logits)
                a_onehot = jax.nn.one_hot(action, self._n_act)
                h2, z2 = self._img_step(wm, h, z, a_onehot, k2)
                return (h2, z2), (feat, logits, action)

            keys = jax.random.split(key, cfg_h)
            (_, _), (feats, logits, actions) = jax.lax.scan(
                step, (h, z), keys)
            return feats, logits, actions  # [H, N, ...]

        def actor_critic_loss(ac_params, params, feat0, key):
            params = {**params, "actor": ac_params["actor"],
                      "critic": ac_params["critic"]}
            feats, logits, actions = imagine(params, feat0, key)
            wm = params["wm"]
            rewards = symexp(_mlp(wm["reward"], feats)[..., 0])
            cont = jax.nn.sigmoid(_mlp(wm["cont"], feats)[..., 0])
            values = symexp(
                _mlp(params["critic"], feats)[..., 0])       # [H, N]
            ema_values = symexp(
                _mlp(params["critic_ema"], feats)[..., 0])
            discount = cfg.gamma * cont

            # lambda-returns computed backward over the horizon with
            # the EMA critic bootstrapping the tail.
            def ret_step(acc, xs):
                r, d, v_next = xs
                acc = r + d * ((1 - cfg.lambda_) * v_next
                               + cfg.lambda_ * acc)
                return acc, acc

            v_next = jnp.concatenate(
                [ema_values[1:], ema_values[-1:]], axis=0)
            _, returns = jax.lax.scan(
                ret_step, ema_values[-1],
                (rewards, discount, v_next), reverse=True)

            returns_sg = jax.lax.stop_gradient(returns)
            # Return normalization (the paper scales by the return
            # range percentile; std is the toy-scale stand-in).
            scale = jnp.maximum(1.0, jnp.std(returns_sg))
            adv = (returns_sg - values) / scale
            logp = jax.nn.log_softmax(logits)
            taken_logp = jnp.take_along_axis(
                logp, actions[..., None], axis=-1)[..., 0]
            entropy = -jnp.sum(jax.nn.softmax(logits) * logp, axis=-1)
            actor_loss = -jnp.mean(
                taken_logp * jax.lax.stop_gradient(adv)
                + cfg.entropy_coeff * entropy)
            critic_pred = _mlp(params["critic"], feats)[..., 0]
            critic_loss = jnp.mean(jnp.square(
                critic_pred - symlog(returns_sg)))
            total = actor_loss + critic_loss
            return total, {"actor_loss": actor_loss,
                           "critic_loss": critic_loss,
                           "actor_entropy": jnp.mean(entropy),
                           "return_mean": jnp.mean(returns_sg)}

        def update(params, opt_state, batch, key):
            k1, k2 = jax.random.split(key)
            (_, (feat, wm_metrics)), wm_grads = jax.value_and_grad(
                world_model_loss, has_aux=True)(params["wm"], batch, k1)
            updates, wm_opt = self._wm_opt.update(
                wm_grads, opt_state["wm"], params["wm"])
            new_wm = optax.apply_updates(params["wm"], updates)

            feat0 = jax.lax.stop_gradient(
                feat.reshape(-1, feat.shape[-1]))
            ac_params = {"actor": params["actor"],
                         "critic": params["critic"]}
            (_, ac_metrics), ac_grads = jax.value_and_grad(
                actor_critic_loss, has_aux=True)(
                    ac_params, {**params, "wm": new_wm}, feat0, k2)
            a_up, actor_opt = self._actor_opt.update(
                ac_grads["actor"], opt_state["actor"], params["actor"])
            new_actor = optax.apply_updates(params["actor"], a_up)
            c_up, critic_opt = self._critic_opt.update(
                ac_grads["critic"], opt_state["critic"],
                params["critic"])
            new_critic = optax.apply_updates(params["critic"], c_up)
            new_ema = jax.tree.map(
                lambda e, c: cfg.critic_ema * e + (1 - cfg.critic_ema)
                * c, params["critic_ema"], new_critic)
            new_params = {"wm": new_wm, "actor": new_actor,
                          "critic": new_critic, "critic_ema": new_ema}
            new_opt = {"wm": wm_opt, "actor": actor_opt,
                       "critic": critic_opt}
            return new_params, new_opt, {**wm_metrics, **ac_metrics}

        return update

    # ---------------------------------------------------------- stepping

    def _collect(self, n_steps: int) -> None:
        cfg = self.algo_config
        for _ in range(n_steps):
            self._rng, sub = jax.random.split(self._rng)
            actions, self._act_state = self._policy_fn(
                self.params, self._act_state, jnp.asarray(self._obs),
                sub)
            actions = np.asarray(actions)
            next_obs, rewards, terms, truncs = self.env.step(actions)
            self._replay.add(self._obs, actions, rewards, terms, truncs)
            dones = terms | truncs
            self._lane_return += rewards
            if dones.any():
                # Reset the live RSSM state (and a_prev) for finished
                # lanes.
                h, z, a_prev = self._act_state
                mask = jnp.asarray(1.0 - dones.astype(np.float32))
                self._act_state = (h * mask[:, None], z * mask[:, None],
                                   a_prev * mask[:, None])
                for i in np.where(dones)[0]:
                    self._episode_returns.append(
                        float(self._lane_return[i]))
                    self._lane_return[i] = 0.0
            self._obs = next_obs
            self._timesteps_total += cfg.num_envs

    def training_step(self) -> dict:
        cfg = self.algo_config
        if self._replay.size < cfg.prefill_steps:
            self._collect(
                (cfg.prefill_steps - self._replay.size + cfg.num_envs - 1)
                // cfg.num_envs)
        # Prefill is counted in TOTAL transitions, but sampling needs
        # per-LANE depth: with many envs, prefill_steps can be met with
        # only a handful of rows per lane — fewer than seq_len — and
        # sample_sequences would raise on the first update. Top up until
        # every lane holds a full BPTT window.
        min_rows = cfg.seq_len + 1
        if self._replay.filled < min_rows:
            self._collect(min_rows - self._replay.filled)
        metrics: dict = {}
        for _ in range(cfg.updates_per_iteration):
            self._collect(max(1, cfg.env_steps_per_update
                              // cfg.num_envs))
            batch = self._replay.sample_sequences(
                self._np_rng, cfg.batch_sequences, cfg.seq_len)
            self._rng, sub = jax.random.split(self._rng)
            self.params, self._opt_state, metrics = self._update_fn(
                self.params, self._opt_state,
                {k: jnp.asarray(v) for k, v in batch.items()}, sub)
        results = {k: float(v) for k, v in metrics.items()}
        recent = self._episode_returns[-50:]
        if recent:
            results["episode_return_mean"] = float(np.mean(recent))
        results["num_env_steps_sampled"] = self._timesteps_total
        return results

    # ------------------------------------------------------- persistence

    def save_checkpoint(self, checkpoint_dir: str):
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "dreamer_state.pkl"),
                  "wb") as f:
            pickle.dump({"params": jax.device_get(self.params),
                         "iteration": self.iteration}, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint) -> None:
        import os
        import pickle

        path = checkpoint if isinstance(checkpoint, str) \
            else checkpoint.path
        with open(os.path.join(path, "dreamer_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.iteration = state["iteration"]

    def cleanup(self) -> None:
        pass

    def _sync_weights(self) -> None:
        pass


class _SequenceReplay:
    """Per-lane ring of transitions; samples contiguous [L] windows
    (reference: dreamerv3's EpisodeReplayBuffer, sequence-sampled)."""

    def __init__(self, capacity: int, num_lanes: int, obs_size: int):
        self.per_lane = max(64, capacity // num_lanes)
        self.num_lanes = num_lanes
        self.obs = np.zeros((num_lanes, self.per_lane, obs_size),
                            dtype=np.float32)
        self.actions = np.zeros((num_lanes, self.per_lane),
                                dtype=np.int32)
        self.rewards = np.zeros((num_lanes, self.per_lane),
                                dtype=np.float32)
        self.terms = np.zeros((num_lanes, self.per_lane),
                              dtype=np.float32)
        self.dones = np.zeros((num_lanes, self.per_lane),
                              dtype=np.float32)
        self.ptr = 0
        self.filled = 0

    @property
    def size(self) -> int:
        return self.filled * self.num_lanes

    def add(self, obs, actions, rewards, terms, truncs) -> None:
        p = self.ptr
        self.obs[:, p] = obs
        self.actions[:, p] = actions
        self.rewards[:, p] = rewards
        self.terms[:, p] = terms.astype(np.float32)
        self.dones[:, p] = (terms | truncs).astype(np.float32)
        self.ptr = (p + 1) % self.per_lane
        self.filled = min(self.filled + 1, self.per_lane)

    def sample_sequences(self, rng, n: int, length: int) -> dict:
        max_start = self.filled - length
        if max_start <= 0:
            raise ValueError("replay has fewer rows than seq_len")
        lanes = rng.integers(0, self.num_lanes, size=n)
        starts = rng.integers(0, max_start, size=n)
        if self.filled == self.per_lane:
            # Ring wrapped: valid data is everywhere, but windows must
            # not straddle the write pointer.
            starts = (self.ptr + starts) % self.per_lane
        idx = (starts[:, None] + np.arange(length)[None, :]) \
            % self.per_lane
        return {
            "obs": self.obs[lanes[:, None], idx],
            "actions": self.actions[lanes[:, None], idx],
            "rewards": self.rewards[lanes[:, None], idx],
            "terminateds": self.terms[lanes[:, None], idx],
            "dones": self.dones[lanes[:, None], idx],
        }


DreamerV3Config.algo_class = DreamerV3
