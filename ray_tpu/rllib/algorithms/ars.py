"""ARS — Augmented Random Search.

Reference: rllib/algorithms/ars/ (Mania et al. 2018). Same
antithetic-perturbation fan-out as ES (each direction is one stateless
remote task regenerating its noise from a seed), with ARS's three
augmentations over basic random search:

- V1/V2 step: update uses only the **top-k directions** ranked by
  max(R+, R-) (``num_top_directions``);
- the step size is **normalized by the reward std** of the selected
  directions (so the learning rate is scale-free);
- raw rewards, not centered ranks, weight the update.

Observation normalization (ARS-V2's running mean/std filter) is left
to the module; CartPole-scale observations don't need it and the
filter state would otherwise have to be merged across tasks.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithms.es import ES, ESConfig


class ARSConfig(ESConfig):
    def __init__(self):
        super().__init__()
        self.population_size = 32        # directions sampled = pop / 2
        self.num_top_directions = 8      # b in the paper (<= pop/2)
        self.sigma = 0.05
        self.lr = 0.02

    def learner_class(self):  # pragma: no cover - ARS has no learner
        return None


class ARS(ES):
    config_class = ARSConfig

    def training_step(self) -> dict:
        cfg = self.algo_config
        pairs = max(1, cfg.population_size // 2)
        top_k = min(max(1, cfg.num_top_directions), pairs)
        results = self._fanout_population(pairs)

        # Rank directions by max(R+, R-) and keep the top k
        # (reference: ars.py top-performing directions selection).
        scored = sorted(results, key=lambda r: max(r[1], r[2]),
                        reverse=True)[:top_k]
        selected = np.array([[rp, rm] for _, rp, rm, _ in scored])
        reward_std = float(selected.std()) or 1.0

        grad = np.zeros_like(self._theta)
        for seed, r_plus, r_minus, _ in scored:
            eps = np.random.default_rng(seed).standard_normal(
                self._theta.shape[0]).astype(np.float32)
            grad += (r_plus - r_minus) * eps
        self._theta = self._theta + (
            cfg.lr / (top_k * reward_std)) * grad

        eval_return = self._eval_mean_policy(results)
        return {
            "episode_return_mean": eval_return,
            "population_reward_mean": float(
                np.array([[rp, rm] for _, rp, rm, _ in results]).mean()),
            "top_direction_reward_mean": float(selected.mean()),
            "num_perturbations": 2 * pairs,
        }


ARSConfig.algo_class = ARS
