"""Multi-agent PPO — independent-learner PPO over a MultiAgentEnv.

Reference: rllib's multi-agent support lives in the config
(`config.multi_agent(policies=..., policy_mapping_fn=...)`,
algorithm_config.py) + MultiAgentEnvRunner + MultiRLModule; PPO itself
is agent-count agnostic. Same factoring here: one PPOLearner per
policy, fragments arrive pre-grouped per policy from the runner
(multi_agent_env_runner.py), and each policy runs the standard PPO
minibatch loop on its own [T*K*B] batch.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.ppo import (
    PPOConfig,
    PPOLearner,
    postprocess_fragment,
)
from ray_tpu.rllib.core.multi_rl_module import MultiRLModuleSpec
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.multi_agent_env import make_multi_agent_env
from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.num_agents = 2
        self.policies: tuple = ("shared",)
        self.policy_mapping_fn: Callable[[str], str] = (
            lambda aid: "shared")
        self.policy_model_configs: dict = {}

    def multi_agent(self, *, num_agents: int | None = None,
                    policies: tuple | list | None = None,
                    policy_mapping_fn: Callable | None = None,
                    policy_model_configs: dict | None = None,
                    ) -> "MultiAgentPPOConfig":
        """Reference: AlgorithmConfig.multi_agent (algorithm_config.py)."""
        if num_agents is not None:
            self.num_agents = num_agents
        if policies is not None:
            self.policies = tuple(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        if policy_model_configs is not None:
            self.policy_model_configs = dict(policy_model_configs)
        return self

    def learner_class(self):
        return PPOLearner

    def marl_spec(self) -> MultiRLModuleSpec:
        probe = make_multi_agent_env(self.env, self.num_agents, 1)
        specs = {}
        for aid in probe.agent_ids:
            pid = self.policy_mapping_fn(aid)
            if pid in specs:
                continue
            specs[pid] = RLModuleSpec(
                module_class=self.module_class,
                observation_size=probe.observation_size(aid),
                num_actions=probe.num_actions(aid),
                action_size=probe.action_size(aid),
                model_config=dict(self.policy_model_configs.get(
                    pid, self.model_config)))
        # Policies declared but mapped to no agent still get modules
        # (reference allows training them via custom mapping later).
        for pid in self.policies:
            if pid not in specs and probe.agent_ids:
                aid = probe.agent_ids[0]
                specs[pid] = RLModuleSpec(
                    module_class=self.module_class,
                    observation_size=probe.observation_size(aid),
                    num_actions=probe.num_actions(aid),
                    action_size=probe.action_size(aid),
                    model_config=dict(self.policy_model_configs.get(
                        pid, self.model_config)))
        return MultiRLModuleSpec(module_specs=specs)


class MultiAgentPPO(Algorithm):
    config_class = MultiAgentPPOConfig

    def setup(self, config: dict) -> None:
        from ray_tpu.rllib.core.learner_group import LearnerGroup

        cfg = self.algo_config
        if cfg.num_learners > 0:
            raise ValueError(
                "MultiAgentPPO runs one local learner per policy; "
                "num_learners > 0 is not supported. Scale the update "
                "over devices with num_devices_per_learner instead "
                "(GSPMD shards each policy's batch over the mesh).")
        self.marl_spec = cfg.marl_spec()
        learner_cls = cfg.learner_class()
        mesh = LearnerGroup._build_local_mesh(cfg.num_devices_per_learner)
        self.learners = {
            pid: learner_cls(spec, config=cfg, mesh=mesh)
            for pid, spec in self.marl_spec.module_specs.items()}
        self.env_runner_group = self._build_env_runners(cfg)
        self._sync_weights()

    def _build_env_runners(self, cfg):
        kwargs = dict(
            env_id=cfg.env, marl_spec=self.marl_spec,
            policy_mapping_fn=cfg.policy_mapping_fn,
            num_agents=cfg.num_agents,
            num_envs=cfg.num_envs_per_env_runner,
            rollout_fragment_length=cfg.rollout_fragment_length,
            seed=cfg.seed, explore=cfg.explore)
        if cfg.num_env_runners <= 0:
            self.local_env_runner = MultiAgentEnvRunner(
                worker_index=0, **kwargs)
            return None
        RemoteRunner = ray_tpu.remote(MultiAgentEnvRunner)

        def factory(idx: int):
            return RemoteRunner.remote(worker_index=idx + 1, **kwargs)

        actors = [factory(i) for i in range(cfg.num_env_runners)]
        self.local_env_runner = None
        return FaultTolerantActorManager(actors, actor_factory=factory)

    def _sync_weights(self) -> None:
        weights = {pid: lrn.get_weights()
                   for pid, lrn in self.learners.items()}
        self._weights_version += 1
        if self.env_runner_group is None:
            self.local_env_runner.set_weights(
                weights, self._weights_version)
        else:
            ref = ray_tpu.put(weights)
            self.env_runner_group.foreach_actor(
                "set_weights", ref, self._weights_version)

    def _sample_fragments(self) -> list[dict]:
        if self.env_runner_group is None:
            frags = [self.local_env_runner.sample()]
        else:
            frags = self.env_runner_group.foreach_actor("sample")
        for frag in frags:
            for batch in frag.values():
                T, B = np.shape(batch["rewards"])[:2]
                self._timesteps_total += T * B
        return frags

    def training_step(self) -> dict:
        cfg = self.algo_config
        fragments = self._sample_fragments()

        results: dict = {}
        rng = np.random.default_rng(cfg.seed + self.iteration)
        for pid, learner in self.learners.items():
            per_policy = [frag[pid] for frag in fragments if pid in frag]
            if not per_policy:
                continue
            train_batch = SampleBatch.concat(
                [postprocess_fragment(f, cfg.gamma, cfg.lambda_)
                 for f in per_policy])
            mb = min(cfg.minibatch_size, len(train_batch))
            metrics: dict = {}
            for _ in range(cfg.num_epochs):
                for minibatch in train_batch.minibatches(mb, rng):
                    metrics = learner.update_from_batch(minibatch)
            results[pid] = metrics
        self._sync_weights()

        results.update(self._runner_metrics())
        return results

    # -- checkpointing ------------------------------------------------
    def save_checkpoint(self, checkpoint_dir: str):
        import os
        import pickle

        state = {
            "learners": {pid: lrn.get_state()
                         for pid, lrn in self.learners.items()},
            "iteration": self.iteration,
            "timesteps": self._timesteps_total,
        }
        with open(os.path.join(checkpoint_dir,
                               "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint) -> None:
        import os
        import pickle

        path = checkpoint if isinstance(checkpoint, str) else checkpoint.path
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        for pid, lrn_state in state["learners"].items():
            self.learners[pid].set_state(lrn_state)
        self.iteration = state["iteration"]
        self._timesteps_total = state["timesteps"]
        self._sync_weights()

    # Rebind the Trainable aliases to the multi-agent implementations
    # (the base class binds `save = Algorithm.save_checkpoint`, which
    # references self.learner_group — never created here).
    save = save_checkpoint
    restore = load_checkpoint

    def cleanup(self) -> None:
        if self.env_runner_group is not None:
            for i in self.env_runner_group.healthy_actor_ids():
                try:
                    ray_tpu.kill(self.env_runner_group.actor(i))
                except Exception:  # noqa: BLE001
                    pass


MultiAgentPPOConfig.algo_class = MultiAgentPPO
