"""CQL — Conservative Q-Learning for offline continuous control.

Reference: rllib/algorithms/cql/ (CQL builds on SAC: the torch learner
adds the conservative penalty to the critic loss and trains purely
from logged data). Here the penalty lives in the shared SAC update
(sac.py: cql_alpha gates it inside the same single jitted program) and
the offline input rides ray_tpu.data — no environment interaction.

Input rows need {"obs": [D], "actions": [A], "rewards": float,
"new_obs"/"next_obs": [D], "terminateds"/"dones": bool}.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, load_offline_rows
from ray_tpu.rllib.algorithms.sac import SACConfig
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.cql_alpha = 1.0
        self.cql_num_sampled_actions = 10
        self.updates_per_iteration = 64
        # offline_data(): a ray_tpu.data Dataset or a list of row dicts.
        self.input_ = None

    def offline_data(self, input_) -> "CQLConfig":
        """Reference: AlgorithmConfig.offline_data(input_=...)."""
        self.input_ = input_
        return self


def _rows_to_transitions(rows: list[dict]) -> SampleBatch:
    def col(*names, default=None):
        out = []
        for row in rows:
            for name in names:
                if name in row:
                    out.append(row[name])
                    break
            else:
                if default is None:
                    raise KeyError(
                        f"offline row missing one of {names}: "
                        f"{sorted(row)}")
                out.append(default)
        return np.asarray(out)

    return SampleBatch({
        Columns.OBS: col("obs").astype(np.float32),
        Columns.ACTIONS: col("actions").astype(np.float32),
        Columns.REWARDS: col("rewards").astype(np.float32),
        Columns.NEXT_OBS: col("new_obs", "next_obs").astype(np.float32),
        Columns.TERMINATEDS: col("terminateds", "dones",
                                 default=False).astype(bool),
    })


class CQL(Algorithm):
    """Offline training loop: dataset -> replay buffer -> N conservative
    SAC updates per iteration (no env runners)."""

    config_class = CQLConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        rows = load_offline_rows(cfg.input_)
        if cfg.num_learners > 0:
            raise ValueError("CQL runs on a local learner (like SAC)")
        super().setup(config)
        batch = _rows_to_transitions(rows)
        self.replay = ReplayBuffer(max(len(rows), 1), seed=cfg.seed)
        self.replay.add(batch)
        self._learner_steps = 0

    def _build_env_runners(self, cfg):
        self.local_env_runner = None  # purely offline
        return None

    def _sync_weights(self) -> None:
        pass  # no runners to sync

    def _runner_metrics(self) -> dict:
        return {}

    def training_step(self) -> dict:
        cfg = self.algo_config
        metrics: dict = {}
        for _ in range(cfg.updates_per_iteration):
            batch = self.replay.sample(
                min(cfg.train_batch_size, len(self.replay)))
            metrics = self.learner_group.update_from_batch(batch)
            self._learner_steps += 1
        metrics["num_learner_steps"] = self._learner_steps
        metrics["dataset_size"] = len(self.replay)
        return metrics


CQLConfig.algo_class = CQL
