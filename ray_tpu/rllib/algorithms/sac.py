"""SAC — Soft Actor-Critic for continuous control.

Reference: rllib/algorithms/sac/ (sac.py config surface; torch learner
sac_torch_learner.py computes the three losses — critic, actor,
alpha — as separate optimizer steps). TPU shape here: ONE jitted
update computes all three losses and applies all three optimizers plus
the polyak target update in a single XLA program — no Python between
them, so the whole SGD step is one device launch.

Components:
- squashed-Gaussian actor: a = tanh(mu + sigma * eps), with the
  tanh-Jacobian log-prob correction;
- twin Q critics (clipped double-Q targets);
- learnable entropy temperature alpha with target entropy
  -|action_size| (the "auto" setting of the reference).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import (
    RLModule,
    RLModuleSpec,
    _mlp_apply,
    _mlp_init,
)
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer
from ray_tpu.rllib.utils.sample_batch import (
    Columns,
    SampleBatch,
    fragment_to_transitions,
)

LOG_STD_MIN = -20.0
LOG_STD_MAX = 2.0


class SACModule(RLModule):
    """Squashed-Gaussian policy + twin Q networks.

    Actions are squashed to the env's symmetric box
    [-action_scale, action_scale]^d (reference: rllib's SquashedGaussian
    distribution scales tanh output to the action-space bounds).
    """

    def __init__(self, observation_size: int, num_actions: int = 0,
                 action_size: int = 1, hidden: tuple = (256, 256),
                 action_scale: float = 1.0, **_):
        assert num_actions == 0, "SAC is continuous-control only"
        self.observation_size = observation_size
        self.action_size = action_size
        self.hidden = tuple(hidden)
        self.action_scale = float(action_scale)

    def init(self, rng):
        pi_rng, q1_rng, q2_rng = jax.random.split(rng, 3)
        obs, act, h = self.observation_size, self.action_size, self.hidden
        return {
            # Actor trunk emits [mu, log_std] stacked.
            "pi": _mlp_init(pi_rng, (obs,) + h + (2 * act,)),
            "q1": _mlp_init(q1_rng, (obs + act,) + h + (1,)),
            "q2": _mlp_init(q2_rng, (obs + act,) + h + (1,)),
        }

    # -- policy ------------------------------------------------------
    def _mu_logstd(self, params, obs):
        out = _mlp_apply(params["pi"], obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        return mu, log_std

    def sample_action(self, params, obs, rng):
        """-> (action in [-s, s]^d, log-prob) with tanh correction.

        Actions are squashed to the env's symmetric box (s =
        ``action_scale``, reference: SquashedGaussian scaling to the
        action-space bounds).
        """
        mu, log_std = self._mu_logstd(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(rng, mu.shape)
        pre_tanh = mu + std * eps
        action = jnp.tanh(pre_tanh) * self.action_scale
        # N(mu, std) logp minus log|d (s*tanh)/dx|, the numerically
        # stable form: log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x)).
        gauss_logp = jnp.sum(
            -0.5 * jnp.square(eps) - log_std
            - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
        correction = jnp.sum(
            2.0 * (jnp.log(2.0) - pre_tanh
                   - jax.nn.softplus(-2.0 * pre_tanh))
            + jnp.log(self.action_scale), axis=-1)
        return action, gauss_logp - correction

    def q_values(self, params, obs, actions):
        x = jnp.concatenate([obs, actions], axis=-1)
        return (_mlp_apply(params["q1"], x)[..., 0],
                _mlp_apply(params["q2"], x)[..., 0])

    # -- RLModule passes ----------------------------------------------
    def forward_inference(self, params, batch, rng=None):
        mu, _ = self._mu_logstd(params, batch["obs"])
        return {"actions": jnp.tanh(mu) * self.action_scale,
                "action_logits": mu,
                "action_logp": jnp.zeros(mu.shape[:-1])}

    def forward_exploration(self, params, batch, rng=None):
        action, logp = self.sample_action(params, batch["obs"], rng)
        return {"actions": action, "action_logp": logp,
                "action_logits": action,
                "vf_preds": jnp.zeros(action.shape[:-1])}

    def forward_train(self, params, batch, rng=None):
        return {}


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "Pendulum-v1"
        self.module_class = SACModule
        self.model_config = {"hidden": (256, 256)}
        self.lr = 3e-4
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.tau = 0.005                     # polyak coefficient
        self.initial_alpha = 1.0
        self.target_entropy = None           # None => -action_size
        self.buffer_capacity = 100_000
        self.train_batch_size = 256
        self.num_steps_sampled_before_learning = 1500
        self.updates_per_iteration = 64

    def learner_class(self):
        return SACLearner


class SACLearner(Learner):
    """All-in-one jitted SAC update (reference splits this into three
    torch optimizer steps in sac_torch_learner.py; here XLA fuses the
    critic/actor/alpha updates and the polyak into one program)."""

    def __init__(self, module_spec: RLModuleSpec, config=None, mesh=None):
        super().__init__(module_spec, config, mesh)
        cfg = self.config
        self.target_params = jax.tree_util.tree_map(
            jnp.copy, {"q1": self.params["q1"], "q2": self.params["q2"]})
        self.log_alpha = jnp.asarray(
            np.log(getattr(cfg, "initial_alpha", 1.0)), dtype=jnp.float32)
        self.target_entropy = (
            cfg.target_entropy if getattr(cfg, "target_entropy", None)
            is not None else -float(self.module.action_size))
        self._alpha_opt = optax.adam(getattr(cfg, "alpha_lr", 3e-4))
        self._alpha_opt_state = self._alpha_opt.init(self.log_alpha)
        self._sac_update = None

    def configure_optimizer(self):
        # One optimizer over {pi, q1, q2}: per-leaf learning rates via
        # masks give actor/critic their own lr like the reference's
        # separate optimizers.
        cfg = self.config
        actor_lr = getattr(cfg, "actor_lr", 3e-4)
        critic_lr = getattr(cfg, "critic_lr", 3e-4)

        def label_fn(params):
            return {k: ("actor" if k == "pi" else "critic")
                    for k in params}

        return optax.multi_transform(
            {"actor": optax.adam(actor_lr),
             "critic": optax.adam(critic_lr)}, label_fn)

    def _build_sac_update(self):
        cfg = self.config
        gamma = cfg.gamma
        tau = getattr(cfg, "tau", 0.005)
        target_entropy = self.target_entropy
        module = self.module

        # CQL hook (reference: rllib/algorithms/cql/ builds on SAC):
        # a conservative penalty alpha_cql * (logsumexp_a Q(s, a) -
        # Q(s, a_data)) keeps offline Q estimates from exploding on
        # out-of-distribution actions. Zero (the default) is plain SAC.
        cql_alpha = float(getattr(cfg, "cql_alpha", 0.0))
        cql_n = int(getattr(cfg, "cql_num_sampled_actions", 10))
        action_scale = float(getattr(module, "action_scale", 1.0))
        action_size = int(getattr(module, "action_size", 1))

        def update(params, opt_state, target_params, log_alpha,
                   alpha_opt_state, batch, rng):
            next_rng, pi_rng, cql_rng = jax.random.split(rng, 3)
            alpha = jnp.exp(log_alpha)

            # --- critic loss: clipped double-Q soft target ----------
            next_a, next_logp = module.sample_action(
                params, batch[Columns.NEXT_OBS], next_rng)
            tq1, tq2 = module.q_values(
                {**params, **target_params},
                batch[Columns.NEXT_OBS], next_a)
            q_next = jnp.minimum(tq1, tq2) - alpha * next_logp
            not_done = 1.0 - batch[Columns.TERMINATEDS].astype(jnp.float32)
            targets = jax.lax.stop_gradient(
                batch[Columns.REWARDS] + gamma * not_done * q_next)

            def critic_loss_fn(p):
                q1, q2 = module.q_values(
                    p, batch[Columns.OBS], batch[Columns.ACTIONS])
                loss = 0.5 * (jnp.mean(jnp.square(q1 - targets))
                              + jnp.mean(jnp.square(q2 - targets)))
                penalty = jnp.zeros(())
                if cql_alpha > 0.0:
                    # CQL(H) with uniform proposals: push down
                    # logsumexp_a Q(s, a), push up Q on data actions.
                    b = batch[Columns.OBS].shape[0]
                    rand_a = jax.random.uniform(
                        cql_rng, (cql_n, b, action_size),
                        minval=-action_scale, maxval=action_scale)
                    rq1, rq2 = jax.vmap(
                        lambda a: module.q_values(
                            p, batch[Columns.OBS], a))(rand_a)
                    lse1 = jax.scipy.special.logsumexp(rq1, axis=0)
                    lse2 = jax.scipy.special.logsumexp(rq2, axis=0)
                    penalty = (jnp.mean(lse1 - q1)
                               + jnp.mean(lse2 - q2))
                    loss = loss + cql_alpha * penalty
                return loss, (q1, penalty)

            # --- actor loss -----------------------------------------
            def actor_loss_fn(p):
                a, logp = module.sample_action(
                    p, batch[Columns.OBS], pi_rng)
                q1, q2 = module.q_values(p, batch[Columns.OBS], a)
                q = jnp.minimum(q1, q2)
                return jnp.mean(alpha * logp - q), (logp,)

            (critic_loss, (q1_vals, cql_penalty)), critic_grads = \
                jax.value_and_grad(critic_loss_fn, has_aux=True)(params)
            (actor_loss, (logp,)), actor_grads = jax.value_and_grad(
                actor_loss_fn, has_aux=True)(params)
            # Actor gradients flow only into pi; critic grads only into
            # q1/q2 (actor loss's q-grads must NOT update the critics —
            # mask them out, mirroring the reference's separate steps).
            grads = {
                "pi": actor_grads["pi"],
                "q1": critic_grads["q1"],
                "q2": critic_grads["q2"],
            }
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)

            # --- alpha loss -----------------------------------------
            def alpha_loss_fn(la):
                return -jnp.mean(jnp.exp(la) * jax.lax.stop_gradient(
                    logp + target_entropy))

            alpha_loss, alpha_grad = jax.value_and_grad(alpha_loss_fn)(
                log_alpha)
            alpha_updates, alpha_opt_state = self._alpha_opt.update(
                alpha_grad, alpha_opt_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, alpha_updates)

            # --- polyak target update -------------------------------
            target_params = jax.tree_util.tree_map(
                lambda t, o: (1 - tau) * t + tau * o,
                target_params, {"q1": params["q1"], "q2": params["q2"]})

            metrics = {
                "critic_loss": critic_loss,
                "actor_loss": actor_loss,
                "alpha_loss": alpha_loss,
                "alpha": alpha,
                "q_mean": jnp.mean(q1_vals),
                "entropy": -jnp.mean(logp),
                "cql_penalty": cql_penalty,
            }
            return (params, opt_state, target_params, log_alpha,
                    alpha_opt_state, metrics)

        return jax.jit(update)

    def update_from_batch(self, batch: SampleBatch,
                          sync_metrics: bool = True) -> dict:
        if self._sac_update is None:
            self._sac_update = self._build_sac_update()
        self._rng, rng = jax.random.split(self._rng)
        arrays = self._device_batch(batch)
        (self.params, self.opt_state, self.target_params, self.log_alpha,
         self._alpha_opt_state, metrics) = self._sac_update(
            self.params, self.opt_state, self.target_params,
            self.log_alpha, self._alpha_opt_state, arrays, rng)
        self._steps += 1
        if not sync_metrics:
            return metrics  # device arrays; caller syncs when it reports
        host = jax.device_get(metrics)  # one transfer for all scalars
        return {k: float(v) for k, v in host.items()}

    def get_state(self) -> dict:
        state = super().get_state()
        state["target_params"] = jax.device_get(self.target_params)
        state["log_alpha"] = jax.device_get(self.log_alpha)
        state["alpha_opt_state"] = jax.device_get(self._alpha_opt_state)
        return state

    def set_state(self, state: dict) -> None:
        super().set_state(state)
        if "target_params" in state:
            self.target_params = state["target_params"]
        if "log_alpha" in state:
            self.log_alpha = state["log_alpha"]
        if "alpha_opt_state" in state:
            self._alpha_opt_state = state["alpha_opt_state"]


class SAC(Algorithm):
    """Off-policy loop: replay buffer of flat transitions, N jitted
    updates per iteration (reference: sac.py training_step via the
    shared DQN-style off-policy skeleton)."""

    config_class = SACConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        if cfg.num_learners > 0:
            raise ValueError(
                "SAC's update (twin-Q + actor + alpha + polyak in one "
                "jitted program) runs on a local learner; num_learners "
                "> 0 is not supported. Scale over devices with "
                "num_devices_per_learner (GSPMD shards the batch).")
        super().setup(config)
        self.replay = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self._learner_steps = 0

    def _fragment_to_transitions(self, frag: SampleBatch) -> SampleBatch:
        return fragment_to_transitions(frag)

    def training_step(self) -> dict:
        cfg = self.algo_config
        for frag in self._sample_fragments():
            self.replay.add(self._fragment_to_transitions(frag))

        metrics: dict = {}
        if len(self.replay) >= cfg.num_steps_sampled_before_learning:
            for _ in range(cfg.updates_per_iteration):
                batch = self.replay.sample(cfg.train_batch_size)
                metrics = self.learner_group.update_from_batch(batch)
                self._learner_steps += 1
            self._sync_weights()

        results = self._runner_metrics()
        results.update(metrics)
        results["replay_buffer_size"] = len(self.replay)
        results["num_learner_steps"] = self._learner_steps
        return results


SACConfig.algo_class = SAC
