"""ES — OpenAI-style Evolution Strategies.

Reference: rllib/algorithms/es/ (Salimans et al. 2017: a population of
parameter perturbations evaluated in parallel; the update is the
rank-weighted sum of the noise directions). The compute shape fits the
task runtime perfectly: each antithetic pair is one stateless remote
task, so evaluation fans out over every core/node the cluster has.

Shared-noise trick (reference: es/utils.py noise table): tasks receive
only (base params ref, seed, sigma) and regenerate their perturbation
from the seed; the driver regenerates the same noise to apply the
update — full parameter vectors never travel per perturbation.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.population_size = 32        # antithetic pairs = pop / 2
        self.sigma = 0.05                # perturbation stddev
        self.lr = 0.02
        self.episodes_per_perturbation = 2
        self.max_episode_steps = 500
        self.report_eval_episodes = 4    # greedy eval of the mean policy

    def learner_class(self):  # pragma: no cover - ES has no learner
        return None


def _policy_step(module):
    """Jitted greedy step, built ONCE per module (jit caches key on
    function identity — a fresh lambda per rollout would recompile
    every call)."""
    import jax

    return jax.jit(
        lambda p, o: module.forward_inference(p, {"obs": o}))


def _rollout_return(step, params, env, max_steps: int) -> tuple:
    """(mean undiscounted episode return over the env's lanes,
    actual env steps taken)."""
    obs = env.reset(seed=0)
    total = np.zeros(env.num_envs)
    alive = np.ones(env.num_envs, dtype=bool)
    steps = 0
    for _ in range(max_steps):
        out = step(params, obs)
        actions = np.asarray(out["actions"])
        obs, rewards, term, trunc = env.step(actions)
        total += rewards * alive
        steps += int(alive.sum())
        alive &= ~(term | trunc)
        if not alive.any():
            break
    return float(np.mean(total)), steps


# Per-process cache: pool workers persist across tasks, and a fresh
# module + jitted lambda per task would pay a full XLA recompile per
# perturbation evaluation.
_EVAL_CACHE: dict = {}


def _cached_policy(spec):
    key = repr((spec.module_class, spec.observation_size,
                spec.num_actions, getattr(spec, "action_size", 0),
                sorted(spec.model_config.items(), key=repr)))
    entry = _EVAL_CACHE.get(key)
    if entry is None:
        import jax
        from jax.flatten_util import ravel_pytree

        module = spec.build()
        template = module.init(jax.random.PRNGKey(0))
        _, unravel = ravel_pytree(template)
        entry = (unravel, _policy_step(module))
        _EVAL_CACHE[key] = entry
    return entry


def _evaluate_pair(spec, flat_params, seed: int, sigma: float,
                   env_id: str, episodes: int, max_steps: int):
    """One antithetic pair: returns (R(theta + sigma*eps),
    R(theta - sigma*eps)) with eps ~ N(0, I) regenerated from seed."""
    from ray_tpu.rllib.env.vector_env import make_vector_env

    unravel, step = _cached_policy(spec)
    eps = np.random.default_rng(seed).standard_normal(
        flat_params.shape[0]).astype(np.float32)
    env = make_vector_env(env_id, episodes)
    r_plus, n_plus = _rollout_return(
        step, unravel(flat_params + sigma * eps), env, max_steps)
    r_minus, n_minus = _rollout_return(
        step, unravel(flat_params - sigma * eps), env, max_steps)
    return seed, r_plus, r_minus, n_plus + n_minus


def _centered_ranks(values: np.ndarray) -> np.ndarray:
    """Fitness shaping: ranks in [-0.5, 0.5] (reference: es utils)."""
    ranks = np.empty(len(values), dtype=np.float32)
    ranks[values.argsort()] = np.arange(len(values), dtype=np.float32)
    return ranks / max(len(values) - 1, 1) - 0.5


class ES(Algorithm):
    config_class = ESConfig

    def setup(self, config: dict) -> None:
        import jax
        from jax.flatten_util import ravel_pytree

        cfg = self.algo_config
        self.module_spec = cfg.module_spec()
        module = self.module_spec.build()
        params = module.init(jax.random.PRNGKey(cfg.seed))
        flat, self._unravel = ravel_pytree(params)
        self._theta = np.asarray(flat, dtype=np.float32)
        self._module = module
        self._policy_step = _policy_step(module)
        self._eval_task = ray_tpu.remote(_evaluate_pair)
        self._rng = np.random.default_rng(cfg.seed)
        self._timesteps_total = 0
        self.iteration = 0
        self.learner_group = None
        self.env_runner_group = None
        self.local_env_runner = None

    def _fanout_population(self, pairs: int) -> list:
        """Evaluate `pairs` antithetic perturbation pairs as remote
        tasks; returns [(seed, R+, R-, steps), ...]. Shared by ES and
        ARS (ars.py) so the fan-out/timeout mechanics live once."""
        cfg = self.algo_config
        seeds = [int(s) for s in
                 self._rng.integers(0, 2 ** 31 - 1, size=pairs)]
        theta_ref = ray_tpu.put(self._theta)
        refs = [self._eval_task.remote(self.module_spec, theta_ref, seed,
                                       cfg.sigma, cfg.env,
                                       cfg.episodes_per_perturbation,
                                       cfg.max_episode_steps)
                for seed in seeds]
        return ray_tpu.get(refs, timeout=600)

    def _eval_mean_policy(self, results: list) -> float:
        """Greedy eval of the unperturbed mean policy; also folds the
        population's real env-step counts into the lifetime total."""
        cfg = self.algo_config
        from ray_tpu.rllib.env.vector_env import make_vector_env

        eval_return, eval_steps = _rollout_return(
            self._policy_step, self._unravel(self._theta),
            make_vector_env(cfg.env, cfg.report_eval_episodes),
            cfg.max_episode_steps)
        # Real env steps from the evaluations, not the worst-case cap.
        self._timesteps_total += (
            sum(n for _, _, _, n in results) + eval_steps)
        return eval_return

    def training_step(self) -> dict:
        cfg = self.algo_config
        pairs = max(1, cfg.population_size // 2)
        results = self._fanout_population(pairs)

        rewards = np.array([[rp, rm] for _, rp, rm, _ in results])
        ranks = _centered_ranks(rewards.reshape(-1)).reshape(rewards.shape)
        grad = np.zeros_like(self._theta)
        for (seed, _, _, _), (rank_p, rank_m) in zip(results, ranks):
            eps = np.random.default_rng(seed).standard_normal(
                self._theta.shape[0]).astype(np.float32)
            grad += (rank_p - rank_m) * eps
        grad /= 2 * pairs * cfg.sigma
        self._theta = self._theta + cfg.lr * grad

        eval_return = self._eval_mean_policy(results)
        return {
            "episode_return_mean": eval_return,
            "population_reward_mean": float(rewards.mean()),
            "population_reward_max": float(rewards.max()),
            "num_perturbations": 2 * pairs,
        }

    def get_policy_params(self):
        return self._unravel(self._theta)

    # -- Trainable protocol (no learner group to checkpoint) ----------
    def save_checkpoint(self, checkpoint_dir: str):
        import os
        import pickle

        with open(os.path.join(checkpoint_dir,
                               "algorithm_state.pkl"), "wb") as f:
            pickle.dump({"theta": self._theta,
                         "iteration": self.iteration,
                         "timesteps": self._timesteps_total}, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint) -> None:
        import os
        import pickle

        path = (checkpoint if isinstance(checkpoint, str)
                else checkpoint.path)
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self._theta = state["theta"]
        self.iteration = state["iteration"]
        self._timesteps_total = state["timesteps"]

    save = save_checkpoint
    restore = load_checkpoint

    def cleanup(self) -> None:
        pass


ESConfig.algo_class = ES
