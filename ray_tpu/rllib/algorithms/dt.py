"""DT — Decision Transformer (offline RL as sequence modeling).

Reference: rllib/algorithms/dt/ (Chen et al. 2021). Trajectories become
token sequences [R̂_1, s_1, a_1, ..., R̂_K, s_K, a_K] (returns-to-go,
state, action embeddings with shared timestep embeddings); a small
causal transformer predicts each action from the tokens before it, and
at evaluation time the SAME model rolls out autoregressively while the
user conditions behavior with a target return.

TPU shape: training is one jitted update over [B, 3K] token grids
(causal masking via a static lower-triangular mask — no dynamic
shapes); windows are sampled host-side from the offline episodes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm,
    AlgorithmConfig,
    load_offline_rows,
)
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.utils.sample_batch import SampleBatch


def _dense_init(key, fan_in: int, *shape):
    return jax.random.normal(key, shape) * (1.0 / np.sqrt(fan_in))


def _layer_norm(x, scale, bias, eps: float = 1e-5):
    mean = x.mean(axis=-1, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * scale + bias


class DTModule(RLModule):
    """Causal transformer over (rtg, state, action) token triples."""

    def __init__(self, observation_size: int, num_actions: int,
                 context_length: int = 20, embed_dim: int = 64,
                 num_layers: int = 2, num_heads: int = 4,
                 max_timestep: int = 1024, **_):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.context_length = context_length
        self.embed_dim = embed_dim
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_timestep = max_timestep

    def init(self, rng):
        D, A, S = self.embed_dim, self.num_actions, self.observation_size
        keys = jax.random.split(rng, 6 + 4 * self.num_layers)
        params = {
            "embed_rtg": {"w": _dense_init(keys[0], 1, 1, D),
                          "b": jnp.zeros((D,))},
            "embed_state": {"w": _dense_init(keys[1], S, S, D),
                            "b": jnp.zeros((D,))},
            "embed_action": {"w": _dense_init(keys[2], A, A, D)},
            "embed_t": _dense_init(keys[3], D, self.max_timestep, D),
            "ln_f": {"scale": jnp.ones((D,)), "bias": jnp.zeros((D,))},
            "head": {"w": _dense_init(keys[4], D, D, A),
                     "b": jnp.zeros((A,))},
            "blocks": [],
        }
        for i in range(self.num_layers):
            k1, k2, k3, k4 = jax.random.split(keys[6 + i], 4)
            params["blocks"].append({
                "ln1": {"scale": jnp.ones((D,)), "bias": jnp.zeros((D,))},
                "attn": {"wqkv": _dense_init(k1, D, D, 3 * D),
                         "wo": _dense_init(k2, D, D, D)},
                "ln2": {"scale": jnp.ones((D,)), "bias": jnp.zeros((D,))},
                "mlp": {"w1": _dense_init(k3, D, D, 4 * D),
                        "b1": jnp.zeros((4 * D,)),
                        "w2": _dense_init(k4, 4 * D, 4 * D, D),
                        "b2": jnp.zeros((D,))},
            })
        return params

    def _block(self, blk, x, causal_mask):
        B, T, D = x.shape
        H = self.num_heads
        h = _layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
        qkv = h @ blk["attn"]["wqkv"]                       # [B, T, 3D]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)
        scores = q @ k.transpose(0, 1, 3, 2) / np.sqrt(D // H)
        scores = jnp.where(causal_mask, scores, -1e9)
        attn = jax.nn.softmax(scores, axis=-1) @ v          # [B,H,T,d]
        attn = attn.transpose(0, 2, 1, 3).reshape(B, T, D)
        x = x + attn @ blk["attn"]["wo"]
        h = _layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
        h = jax.nn.gelu(h @ blk["mlp"]["w1"] + blk["mlp"]["b1"])
        return x + h @ blk["mlp"]["w2"] + blk["mlp"]["b2"]

    def action_logits(self, params, rtg, obs, actions, timesteps):
        """rtg [B,K], obs [B,K,S], actions [B,K] (logged; the token at
        position t is only attended AFTER predicting a_t thanks to the
        causal mask), timesteps [B,K] -> logits [B,K,A] at the state
        positions."""
        B, K = rtg.shape
        D = self.embed_dim
        t_emb = params["embed_t"][jnp.clip(
            timesteps, 0, self.max_timestep - 1)]           # [B,K,D]
        r_tok = (rtg[..., None] @ params["embed_rtg"]["w"]
                 + params["embed_rtg"]["b"]) + t_emb
        s_tok = (obs @ params["embed_state"]["w"]
                 + params["embed_state"]["b"]) + t_emb
        a_onehot = jax.nn.one_hot(actions, self.num_actions)
        a_tok = a_onehot @ params["embed_action"]["w"] + t_emb
        # Interleave [r_1 s_1 a_1 r_2 s_2 a_2 ...] -> [B, 3K, D].
        tokens = jnp.stack([r_tok, s_tok, a_tok],
                           axis=2).reshape(B, 3 * K, D)
        T = 3 * K
        causal = jnp.tril(jnp.ones((T, T), dtype=bool))[None, None]
        x = tokens
        for blk in params["blocks"]:
            x = self._block(blk, x, causal)
        x = _layer_norm(x, params["ln_f"]["scale"],
                        params["ln_f"]["bias"])
        # Predict a_t from the STATE token at position 3t+1.
        state_positions = x[:, 1::3]                        # [B, K, D]
        return state_positions @ params["head"]["w"] + params[
            "head"]["b"]

    # RLModule protocol: used by the eval rollout (batch carries the
    # whole context).
    def forward_inference(self, params, batch, rng=None):
        logits = self.action_logits(
            params, batch["rtg"], batch["obs"], batch["actions"],
            batch["timesteps"])
        last = logits[:, -1]
        return {"action_logits": last,
                "actions": jnp.argmax(last, axis=-1)}

    forward_exploration = forward_inference

    def forward_train(self, params, batch, rng=None):
        return {"action_logits": self.action_logits(
            params, batch["rtg"], batch["obs"], batch["actions"],
            batch["timesteps"])}


class DTConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.module_class = DTModule
        self.lr = 1e-3
        self.context_length = 20
        self.embed_dim = 64
        self.num_layers = 2
        self.num_heads = 4
        self.train_batch_size = 64
        self.updates_per_iteration = 50
        self.rtg_scale = 100.0       # returns-to-go normalizer
        self.input_ = None
        # Evaluation: greedy autoregressive rollouts conditioned on
        # this target return (reference: dt evaluation).
        self.target_return = 200.0
        self.evaluation_num_episodes = 0
        self.max_eval_steps = 500

    def offline_data(self, input_) -> "DTConfig":
        self.input_ = input_
        return self

    def evaluation(self, *, evaluation_num_episodes: int | None = None,
                   target_return: float | None = None) -> "DTConfig":
        if evaluation_num_episodes is not None:
            self.evaluation_num_episodes = evaluation_num_episodes
        if target_return is not None:
            self.target_return = target_return
        return self

    def learner_class(self):
        return DTLearner

    def module_spec(self):
        spec = super().module_spec()
        spec.model_config.setdefault("context_length",
                                     self.context_length)
        spec.model_config.setdefault("embed_dim", self.embed_dim)
        spec.model_config.setdefault("num_layers", self.num_layers)
        spec.model_config.setdefault("num_heads", self.num_heads)
        return spec


class DTLearner(Learner):
    """Masked cross-entropy on logged actions at every context
    position (reference: dt/dt_torch_policy.py loss)."""

    def compute_loss(self, params, batch, rng):
        logits = self.module.action_logits(
            params, batch["rtg"], batch["obs"], batch["actions"],
            batch["timesteps"])                             # [B,K,A]
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(
            logp, batch["actions"][..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        mask = batch["mask"].astype(jnp.float32)
        loss = -(picked * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        acc = ((logits.argmax(-1) == batch["actions"]) * mask).sum() \
            / jnp.maximum(mask.sum(), 1.0)
        return loss, {"action_ce": loss, "action_accuracy": acc}


def _episodes_from_rows(rows: list[dict], rtg_scale: float) -> list[dict]:
    """Offline rows -> episodes with per-step returns-to-go."""
    episodes, cur = [], []
    for row in rows:
        cur.append(row)
        if row.get("terminateds") or row.get("truncateds"):
            episodes.append(cur)
            cur = []
    if cur:
        episodes.append(cur)
    out = []
    for ep in episodes:
        rewards = np.asarray([float(r.get("rewards", 0.0))
                              for r in ep], dtype=np.float32)
        rtg = np.cumsum(rewards[::-1])[::-1] / rtg_scale
        out.append({
            "obs": np.asarray([r["obs"] for r in ep], dtype=np.float32),
            "actions": np.asarray([r["actions"] for r in ep]),
            "rtg": rtg.astype(np.float32),
            "timesteps": np.arange(len(ep), dtype=np.int32),
        })
    return out


class DT(Algorithm):
    config_class = DTConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        if cfg.num_learners > 0:
            raise ValueError("DT runs on a local learner")
        super().setup(config)
        self._episodes = _episodes_from_rows(
            load_offline_rows(cfg.input_), cfg.rtg_scale)
        if not self._episodes:
            raise ValueError("DT: offline input produced no episodes")
        # Sample episodes proportional to length (every timestep
        # equally likely — reference dt's SegmentationBuffer).
        lens = np.asarray([len(e["actions"]) for e in self._episodes])
        self._ep_probs = lens / lens.sum()
        self._rng = np.random.default_rng(cfg.seed)
        self._learner_steps = 0
        # Built once: the jitted eval fn closes over this module.
        self.module = self.module_spec.build()

    def _build_env_runners(self, cfg):
        self.local_env_runner = None  # offline; eval rolls out itself
        return None

    def _sync_weights(self) -> None:
        self._weights_version += 1

    def _runner_metrics(self) -> dict:
        return {}

    def _sample_windows(self, batch_size: int) -> SampleBatch:
        cfg = self.algo_config
        K = cfg.context_length
        S = self.module_spec.observation_size
        cols = {"rtg": np.zeros((batch_size, K), np.float32),
                "obs": np.zeros((batch_size, K, S), np.float32),
                "actions": np.zeros((batch_size, K), np.int64),
                "timesteps": np.zeros((batch_size, K), np.int32),
                "mask": np.zeros((batch_size, K), np.float32)}
        ep_idx = self._rng.choice(len(self._episodes), size=batch_size,
                                  p=self._ep_probs)
        for i, ei in enumerate(ep_idx):
            ep = self._episodes[ei]
            L = len(ep["actions"])
            end = int(self._rng.integers(1, L + 1))
            start = max(0, end - K)
            n = end - start
            # RIGHT-align so the prediction target sits at the last
            # position (same layout the eval rollout feeds).
            cols["rtg"][i, K - n:] = ep["rtg"][start:end]
            cols["obs"][i, K - n:] = ep["obs"][start:end]
            cols["actions"][i, K - n:] = ep["actions"][start:end]
            cols["timesteps"][i, K - n:] = ep["timesteps"][start:end]
            cols["mask"][i, K - n:] = 1.0
        return SampleBatch(cols)

    def training_step(self) -> dict:
        cfg = self.algo_config
        metrics: dict = {}
        for _ in range(cfg.updates_per_iteration):
            metrics = self.learner_group.update_from_batch(
                self._sample_windows(cfg.train_batch_size))
            self._learner_steps += 1
        results = dict(metrics)
        results["num_learner_steps"] = self._learner_steps
        if cfg.evaluation_num_episodes > 0:
            results["evaluation_return_mean"] = self._evaluate(cfg)
        return results

    def _evaluate(self, cfg) -> float:
        """Greedy autoregressive rollouts conditioned on the target
        return (reference: dt eval loop). B parallel env lanes, one
        jitted forward per step over the K-window context."""
        from ray_tpu.rllib.env.vector_env import make_vector_env

        module = self.module
        params = self.learner_group.get_weights()
        if not hasattr(self, "_eval_fn"):
            self._eval_fn = jax.jit(
                lambda p, b: module.forward_inference(p, b))
        K = cfg.context_length
        env = make_vector_env(cfg.env, cfg.evaluation_num_episodes)
        B = env.num_envs
        S = self.module_spec.observation_size
        obs = env.reset(seed=cfg.seed + 17)
        hist = {"rtg": np.zeros((B, 0), np.float32),
                "obs": np.zeros((B, 0, S), np.float32),
                "actions": np.zeros((B, 0), np.int64),
                "timesteps": np.zeros((B, 0), np.int32)}
        rtg_left = np.full(B, cfg.target_return / cfg.rtg_scale,
                           np.float32)
        totals = np.zeros(B)
        alive = np.ones(B, bool)
        for t in range(cfg.max_eval_steps):
            hist["rtg"] = np.concatenate(
                [hist["rtg"], rtg_left[:, None]], axis=1)[:, -K:]
            hist["obs"] = np.concatenate(
                [hist["obs"], obs[:, None]], axis=1)[:, -K:]
            # Current step's action token is unknown: feed 0 (masked by
            # causality — position 3t+1 never attends to it).
            hist["actions"] = np.concatenate(
                [hist["actions"], np.zeros((B, 1), np.int64)],
                axis=1)[:, -K:]
            hist["timesteps"] = np.concatenate(
                [hist["timesteps"],
                 np.full((B, 1), min(t, module.max_timestep - 1),
                         np.int32)],
                axis=1)[:, -K:]
            n = hist["rtg"].shape[1]
            pad = K - n
            batch = {k: np.pad(v, ((0, 0), (pad, 0)) + ((0, 0),) * (
                v.ndim - 2)) for k, v in hist.items()}
            out = self._eval_fn(params, batch)
            actions = np.asarray(out["actions"])
            hist["actions"][:, -1] = actions
            obs, rewards, term, trunc = env.step(actions)
            totals += rewards * alive
            rtg_left = rtg_left - (rewards / cfg.rtg_scale) * alive
            alive &= ~(term | trunc)
            if not alive.any():
                break
        return float(np.mean(totals))


DTConfig.algo_class = DT
