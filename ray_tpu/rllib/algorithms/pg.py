"""PG / A2C — vanilla policy gradient and synchronous advantage A-C.

Reference: rllib/algorithms/pg/ (REINFORCE: loss = -logp * return-to-go,
no critic, no clipping) and rllib/algorithms/a2c/ (synchronous A3C:
n-step bootstrapped advantages, shared actor-critic loss, one SGD pass
per sampling round — PPO without the ratio clip or epochs).

Both ride the PPO postprocessing path: PG sets lambda=1 and discards
the value baseline in the loss (using raw discounted returns), A2C
uses GAE(lambda) advantages with a single full-batch update per round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import postprocess_fragment
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import (
    categorical_entropy,
    categorical_logp,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class PGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 4e-3
        self.entropy_coeff = 0.0
        # REINFORCE-with-baseline: GAE with lambda=1 gives discounted
        # returns-to-go minus V(s); fragments shorter than an episode
        # bootstrap through V at the cut, so the baseline MUST train
        # (vf_loss below) or the bootstrap is frozen random noise.
        self.lambda_ = 1.0
        self.vf_loss_coeff = 0.5

    def learner_class(self):
        return PGLearner


class PGLearner(Learner):
    """-logp * return loss (reference: pg/torch/pg_torch_policy.py)
    plus a trained value baseline: the reference assumes complete
    episodes per batch; with fixed-length fragments the return-to-go
    bootstraps from V at fragment ends, so V is fit to the value
    targets to keep that bootstrap meaningful."""

    def compute_loss(self, params, batch, rng):
        cfg = self.config
        out = self.module.forward_train(params, batch, rng)
        logits = out["action_logits"]
        logp = categorical_logp(logits, batch[Columns.ACTIONS])
        # postprocess_fragment normalizes advantages; for REINFORCE the
        # normalized advantage is still a valid (variance-reduced)
        # return signal, so use it directly.
        pg_loss = -jnp.mean(logp * batch[Columns.ADVANTAGES])
        vf_loss = jnp.mean(jnp.square(
            out["vf_preds"] - batch[Columns.VALUE_TARGETS]))
        entropy = categorical_entropy(logits)
        total = (pg_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * jnp.mean(entropy))
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": jnp.mean(entropy)}


class PG(Algorithm):
    config_class = PGConfig

    def training_step(self) -> dict:
        cfg = self.algo_config
        fragments = self._sample_fragments()
        train_batch = SampleBatch.concat(
            [postprocess_fragment(f, cfg.gamma, cfg.lambda_)
             for f in fragments])
        metrics = self.learner_group.update_from_batch(train_batch)
        self._sync_weights()

        results = self._runner_metrics()
        results.update(metrics)
        results["num_env_steps_trained"] = len(train_batch)
        return results


PGConfig.algo_class = PG


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.lambda_ = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        # A2C applies ONE synchronous optimizer step per sampling round
        # (reference: a2c.py training_step); microbatch_size splits the
        # forward/backward into chunks whose gradients are accumulated
        # before the single apply (memory cap, same dynamics).
        self.microbatch_size = None

    def learner_class(self):
        return A2CLearner


class A2CLearner(Learner):
    """Shared actor-critic loss (reference: a2c/a2c_torch_policy.py):
    -logp*A + vf_coeff*mse(V, target) - entropy_coeff*H."""

    def compute_loss(self, params, batch, rng):
        cfg = self.config
        out = self.module.forward_train(params, batch, rng)
        logits = out["action_logits"]
        values = out["vf_preds"]
        logp = categorical_logp(logits, batch[Columns.ACTIONS])
        pg_loss = -jnp.mean(logp * batch[Columns.ADVANTAGES])
        vf_loss = jnp.mean(
            jnp.square(values - batch[Columns.VALUE_TARGETS]))
        entropy = jnp.mean(categorical_entropy(logits))
        total = (pg_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}


class A2C(Algorithm):
    config_class = A2CConfig

    def training_step(self) -> dict:
        cfg = self.algo_config
        fragments = self._sample_fragments()
        train_batch = SampleBatch.concat(
            [postprocess_fragment(f, cfg.gamma, cfg.lambda_)
             for f in fragments])

        mb = cfg.microbatch_size
        metrics: dict = {}
        # Multi-learner groups already split the batch across actors
        # (a per-actor accumulate would drift learner 0); local-learner
        # accumulation is the memory-capped path.
        if mb is None or mb >= len(train_batch) or cfg.num_learners > 0:
            metrics = self.learner_group.update_from_batch(train_batch)
            trained = len(train_batch)
        else:
            # Gradient accumulation: N forward/backward chunks, ONE
            # optimizer apply — identical dynamics to the full-batch
            # step at a fraction of the activation memory.
            rng = np.random.default_rng(cfg.seed + self.iteration)
            grads_sum = None
            metrics_list = []
            trained = 0
            for minibatch in train_batch.minibatches(mb, rng):
                g, m = self.learner_group.call(
                    "compute_gradients", minibatch)
                metrics_list.append(m)
                trained += len(minibatch)
                # Row-weighted sum: each chunk's per-row mean gradient
                # scaled by its row count, so a smaller final chunk
                # contributes exactly its share (sum len*g / total ==
                # the full-batch per-row mean).
                w = float(len(minibatch))
                g = jax.tree_util.tree_map(lambda x: x * w, g)
                grads_sum = g if grads_sum is None else (
                    jax.tree_util.tree_map(jnp.add, grads_sum, g))
            self.learner_group.call(
                "apply_gradients",
                jax.tree_util.tree_map(lambda x: x / trained, grads_sum))
            metrics = {k: float(np.mean([float(m[k])
                                         for m in metrics_list]))
                       for k in metrics_list[0]}
        self._sync_weights()

        results = self._runner_metrics()
        results.update(metrics)
        results["num_env_steps_trained"] = trained
        return results


A2CConfig.algo_class = A2C
