"""R2D2 — Recurrent Replay Distributed DQN.

Reference: rllib/algorithms/r2d2/ (Kapturowski et al. 2019). Q-learning
over SEQUENCES with a recurrent (GRU) Q-network:

- env runners thread the GRU state through the rollout and record the
  state at each fragment's first step (env_runner.py recurrent path);
- replay stores whole sequences with their initial state
  (PrioritizedSequenceReplayBuffer), prioritized by the eta-mix of max
  and mean TD magnitude over the sequence;
- the learner unrolls online and target networks over [T, B] with one
  `lax.scan` each (state zeroed at in-sequence episode boundaries),
  applies double-Q targets, masks a burn-in prefix out of the loss
  (those steps only warm the state), and masks truncated steps (their
  true next-state value is unknown).

The whole update is ONE jitted program; the scan keeps the time
dimension on device, so sequence length never touches Python.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import TargetNetworkLearner
from ray_tpu.rllib.core.rl_module import (
    RLModule,
    _mlp_apply,
    _mlp_init,
)
from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedSequenceReplayBuffer,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


def _gru_init(rng, in_size: int, hidden: int) -> dict:
    kx, kh = jax.random.split(rng)
    scale_x = 1.0 / np.sqrt(in_size)
    scale_h = 1.0 / np.sqrt(hidden)
    return {
        "wx": jax.random.uniform(kx, (in_size, 3 * hidden),
                                 minval=-scale_x, maxval=scale_x),
        "wh": jax.random.uniform(kh, (hidden, 3 * hidden),
                                 minval=-scale_h, maxval=scale_h),
        "b": jnp.zeros((3 * hidden,)),
    }


def _gru_cell(params: dict, x, h):
    """Standard GRU cell: fused [r, z, n] gates."""
    gates_x = x @ params["wx"] + params["b"]
    gates_h = h @ params["wh"]
    H = h.shape[-1]
    r = jax.nn.sigmoid(gates_x[..., :H] + gates_h[..., :H])
    z = jax.nn.sigmoid(gates_x[..., H:2 * H] + gates_h[..., H:2 * H])
    n = jnp.tanh(gates_x[..., 2 * H:] + r * gates_h[..., 2 * H:])
    return (1.0 - z) * n + z * h


class GRUQModule(RLModule):
    """Encoder MLP -> GRU -> Q head; epsilon-greedy exploration with
    the same traced decay clock as the feed-forward DQN module."""

    is_recurrent = True

    def __init__(self, observation_size: int, num_actions: int,
                 hidden: tuple = (64,), gru_hidden: int = 64,
                 epsilon_start: float = 1.0, epsilon_end: float = 0.05,
                 epsilon_decay_steps: int = 10_000, **_):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.hidden = tuple(hidden)
        self.gru_hidden = gru_hidden
        self.epsilon_start = epsilon_start
        self.epsilon_end = epsilon_end
        self.epsilon_decay_steps = epsilon_decay_steps

    def init(self, rng):
        k_enc, k_gru, k_q = jax.random.split(rng, 3)
        enc_sizes = (self.observation_size,) + self.hidden
        return {
            "enc": _mlp_init(k_enc, enc_sizes),
            "gru": _gru_init(k_gru, self.hidden[-1], self.gru_hidden),
            "q": _mlp_init(k_q, (self.gru_hidden, self.num_actions)),
        }

    def initial_state(self, batch_size: int) -> np.ndarray:
        return np.zeros((batch_size, self.gru_hidden), dtype=np.float32)

    def _q_step(self, params, obs, h):
        x = _mlp_apply(params["enc"], obs)
        h2 = _gru_cell(params["gru"], x, h)
        return _mlp_apply(params["q"], h2), h2

    def unroll(self, params, obs_seq, state0, reset_mask):
        """Q over a [T, B] sequence: `lax.scan` of the cell, zeroing
        state where reset_mask[t] marks an in-sequence episode start.
        -> q_seq [T, B, A]."""
        def scan_fn(h, xs):
            obs_t, reset_t = xs
            h = h * (1.0 - reset_t)[:, None]
            q_t, h = self._q_step(params, obs_t, h)
            return h, q_t

        _, q_seq = jax.lax.scan(scan_fn, state0, (obs_seq, reset_mask))
        return q_seq

    # -- single-step forwards (rollout path) --------------------------
    def forward_inference(self, params, batch, rng=None):
        q, h2 = self._q_step(params, batch["obs"], batch["state_in"])
        return {"action_logits": q, "actions": jnp.argmax(q, axis=-1),
                "state_out": h2}

    def forward_exploration(self, params, batch, rng=None):
        q, h2 = self._q_step(params, batch["obs"], batch["state_in"])
        greedy = jnp.argmax(q, axis=-1)
        t = batch.get("t", self.epsilon_decay_steps)
        frac = jnp.clip(t / self.epsilon_decay_steps, 0.0, 1.0)
        eps = self.epsilon_start + frac * (
            self.epsilon_end - self.epsilon_start)
        explore_rng, action_rng = jax.random.split(rng)
        random_actions = jax.random.randint(
            action_rng, greedy.shape, 0, self.num_actions)
        take_random = jax.random.uniform(explore_rng, greedy.shape) < eps
        return {"action_logits": q,
                "actions": jnp.where(take_random, random_actions, greedy),
                "action_logp": jnp.zeros_like(q[..., 0]),
                "vf_preds": jnp.max(q, axis=-1),
                "state_out": h2}

    def forward_train(self, params, batch, rng=None):
        q, _ = self._q_step(params, batch["obs"], batch["state_in"])
        return {"action_logits": q}


class R2D2Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.module_class = GRUQModule
        self.lr = 1e-3
        self.rollout_fragment_length = 40   # = stored sequence length
        self.burn_in = 8                    # state-warmup steps, no loss
        self.replay_capacity_sequences = 4096
        self.replay_alpha = 0.6
        self.replay_beta = 0.4
        self.priority_eta = 0.9             # eta*max + (1-eta)*mean TD
        self.train_batch_size = 32          # SEQUENCES per update
        self.target_update_freq = 100
        self.num_sequences_before_learning = 64
        self.updates_per_iteration = 16
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 10_000
        # Learner recomputes Q under grad; runners ship only the core
        # sequence columns.
        self.runner_emit_columns = ()

    def learner_class(self):
        return R2D2Learner

    def module_spec(self):
        spec = super().module_spec()
        spec.model_config.setdefault("epsilon_start", self.epsilon_start)
        spec.model_config.setdefault("epsilon_end", self.epsilon_end)
        spec.model_config.setdefault("epsilon_decay_steps",
                                     self.epsilon_decay_steps)
        return spec


def _reset_mask(terminateds, truncateds):
    """reset_mask[t] = episode boundary BEFORE step t (the stored
    initial state covers t=0, so row 0 is never reset)."""
    done = jnp.logical_or(terminateds, truncateds).astype(jnp.float32)
    return jnp.concatenate(
        [jnp.zeros_like(done[:1]), done[:-1]], axis=0)


class R2D2Learner(TargetNetworkLearner):
    batch_axis = 1  # [T, B]: shard over sequences, scan stays local

    def compute_loss(self, params, batch, rng):
        cfg = self.config
        obs = batch[Columns.OBS]                       # [T, B, D]
        actions = batch[Columns.ACTIONS].astype(jnp.int32)
        rewards = batch[Columns.REWARDS]
        term = batch[Columns.TERMINATEDS].astype(jnp.float32)
        trunc = batch[Columns.TRUNCATEDS].astype(jnp.float32)
        T = rewards.shape[0]
        reset = _reset_mask(batch[Columns.TERMINATEDS],
                            batch[Columns.TRUNCATEDS])

        q_online = self.module.unroll(params, obs, batch["state_in"],
                                      reset)                 # [T, B, A]
        q_target = self.module.unroll(batch["target_params"], obs,
                                      batch["state_in"], reset)
        q_taken = jnp.take_along_axis(
            q_online, actions[..., None], axis=-1)[..., 0]   # [T, B]

        # Double-Q one-step targets from the NEXT row of the sequence:
        # online argmax, target eval.
        next_actions = jnp.argmax(q_online[1:], axis=-1)     # [T-1, B]
        q_next = jnp.take_along_axis(
            q_target[1:], next_actions[..., None], axis=-1)[..., 0]
        targets = rewards[:-1] + cfg.gamma * (1.0 - term[:-1]) * q_next
        td = q_taken[:-1] - jax.lax.stop_gradient(targets)   # [T-1, B]

        # Valid steps: past burn-in, not truncated (no true next
        # value), and the next row must belong to the SAME episode
        # unless the step terminated (then the target is just r).
        steps = jnp.arange(T - 1)[:, None]
        valid = ((steps >= cfg.burn_in)
                 & (trunc[:-1] < 0.5)).astype(jnp.float32)
        weights = batch.get(
            "weights", jnp.ones_like(td[0]))[None, :]        # [1, B]
        denom = jnp.maximum(valid.sum(), 1.0)
        loss = jnp.sum(weights * valid * jnp.square(td)) / denom

        abs_td = jnp.abs(td) * valid
        eta = cfg.priority_eta
        # Per-sequence priorities come straight out of the TRAINING TD
        # errors (the paper's choice): the update already computed
        # them, so no second unroll or batch round trip is ever paid.
        seq_priority = (eta * abs_td.max(axis=0)
                        + (1 - eta) * abs_td.sum(axis=0)
                        / jnp.maximum(valid.sum(axis=0), 1.0))
        return loss, {"td_error_mean": abs_td.sum() / denom,
                      "q_mean": jnp.mean(q_taken),
                      "seq_priority": seq_priority}

    def update_from_batch(self, batch: SampleBatch,
                          sync_metrics: bool = True) -> dict:
        # Target injection + refresh come from TargetNetworkLearner;
        # this override only peels the per-sequence priority ARRAY out
        # of the metrics pytree (one transfer with everything else,
        # stashed for get_last_seq_priorities — never float()-coerced).
        metrics = dict(super().update_from_batch(
            batch, sync_metrics=False))
        prio = metrics.pop("seq_priority", None)
        self._last_seq_priorities = (np.asarray(prio)
                                     if prio is not None else None)
        if not sync_metrics:
            return metrics
        host = jax.device_get(metrics)
        return {k: float(v) for k, v in host.items()}

    def get_last_seq_priorities(self):
        return getattr(self, "_last_seq_priorities", None)


class R2D2(Algorithm):
    config_class = R2D2Config

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        if cfg.num_learners > 0:
            # Round-robin actor updates would slow each actor's
            # target-refresh cadence by N and desync the priorities;
            # the local learner's mesh already covers multi-device.
            raise ValueError(
                "R2D2 runs on a local learner "
                "(num_devices_per_learner scales it across devices)")
        super().setup(config)
        self.replay = PrioritizedSequenceReplayBuffer(
            cfg.replay_capacity_sequences, alpha=cfg.replay_alpha,
            beta=cfg.replay_beta, seed=cfg.seed)
        self._learner_steps = 0

    def training_step(self) -> dict:
        cfg = self.algo_config
        for frag in self._sample_fragments():
            self.replay.add_fragment(frag)

        metrics: dict = {}
        if len(self.replay) >= cfg.num_sequences_before_learning:
            for _ in range(cfg.updates_per_iteration):
                batch = self.replay.sample(cfg.train_batch_size)
                indexes = batch.pop("batch_indexes")
                metrics = self.learner_group.update_from_batch(
                    batch, shard=False)
                self._learner_steps += 1
                prios = self.learner_group.call(
                    "get_last_seq_priorities")
                if prios is not None:
                    self.replay.update_priorities(indexes, prios)
            self._sync_weights()

        results = self._runner_metrics()
        results.update(metrics)
        results["replay_sequences"] = len(self.replay)
        results["num_learner_steps"] = self._learner_steps
        return results


R2D2Config.algo_class = R2D2
