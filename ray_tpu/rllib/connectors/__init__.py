"""ConnectorV2-lite — composable batch transforms.

Reference: rllib/connectors/ (ConnectorV2 pipelines between env, module
and learner). Here a connector is any callable ``(batch) -> batch``;
``ConnectorPipeline`` composes them. Kept deliberately functional: a
pipeline of pure transforms can be fused into the jitted update when
every piece is jax-traceable.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from ray_tpu.rllib.utils.sample_batch import SampleBatch


class ConnectorPipeline:
    """Ordered list of batch transforms (reference: ConnectorPipelineV2)."""

    def __init__(self, connectors: "list[Callable] | None" = None):
        self.connectors = list(connectors or [])

    def append(self, connector: Callable) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: Callable) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def __call__(self, batch: SampleBatch) -> SampleBatch:
        for c in self.connectors:
            batch = c(batch)
        return batch


class NormalizeObservations:
    """Running mean/std observation filter (reference:
    rllib MeanStdFilter connector)."""

    def __init__(self, epsilon: float = 1e-8):
        self.mean = None
        self.var = None
        self.count = epsilon
        self.eps = epsilon

    def __call__(self, batch: SampleBatch) -> SampleBatch:
        obs = np.asarray(batch["obs"], dtype=np.float64)
        flat = obs.reshape(-1, obs.shape[-1])
        if self.mean is None:
            self.mean = np.zeros(obs.shape[-1])
            self.var = np.ones(obs.shape[-1])
        batch_mean = flat.mean(axis=0)
        batch_var = flat.var(axis=0)
        n = flat.shape[0]
        delta = batch_mean - self.mean
        total = self.count + n
        self.mean = self.mean + delta * n / total
        self.var = (self.var * self.count + batch_var * n
                    + delta**2 * self.count * n / total) / total
        self.count = total
        out = SampleBatch(batch)
        out["obs"] = ((obs - self.mean)
                      / np.sqrt(self.var + self.eps)).astype(np.float32)
        return out


class ClipRewards:
    def __init__(self, limit: float = 1.0):
        self.limit = limit

    def __call__(self, batch: SampleBatch) -> SampleBatch:
        out = SampleBatch(batch)
        out["rewards"] = np.clip(
            np.asarray(batch["rewards"]), -self.limit, self.limit)
        return out


__all__ = ["ConnectorPipeline", "NormalizeObservations", "ClipRewards"]
