"""EnvRunner — rollout collection actors.

Reference: rllib/env/env_runner.py (EnvRunner API) and
single_agent_env_runner.py:27/:125 (SingleAgentEnvRunner.sample — the
rollout hot loop). Design differences for TPU:

- envs are stepped as a batched vector env (numpy), so the policy
  forward is ONE jitted call over [B, obs] per env step — the classic
  per-env Python loop never appears;
- the runner keeps module params as a host-local pytree; inference runs
  on whatever backend jit picks (CPU for rollout actors, so the TPU
  stays dedicated to the learner);
- output is a time-major SampleBatch fragment [T, B], which is exactly
  the layout GAE/V-trace scans want — no transpose on the learner.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.vector_env import make_vector_env
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class SingleAgentEnvRunner:
    """Collects fixed-length rollout fragments from a vector env."""

    def __init__(self, *, env_id: str, module_spec: RLModuleSpec,
                 num_envs: int = 8, rollout_fragment_length: int = 64,
                 seed: int = 0, worker_index: int = 0,
                 explore: bool = True, inference_backend: str = "cpu"):
        self.worker_index = worker_index
        # Rollout inference defaults to the CPU backend: per-step policy
        # calls are tiny and latency-bound, and pinning them to CPU keeps
        # the TPU dedicated to the learner (the reference gets this for
        # free because env runners are plain CPU actors).
        try:
            self._device = jax.local_devices(backend=inference_backend)[0]
        except RuntimeError:
            self._device = None
        self.env = make_vector_env(env_id, num_envs)
        self.module = module_spec.build()
        self.rollout_fragment_length = rollout_fragment_length
        self.explore = explore
        # The PRNG key is derived *inside* the jitted step from a host
        # integer, so no device-committed key ever leaks across backends
        # (host ints are uncommitted; execution stays on the rollout
        # device).
        self._seed_base = np.uint32((seed * 100003 + worker_index * 7919)
                                    & 0x7FFFFFFF)
        self._step_counter = 0
        self._weights = None
        self._weights_version = -1
        self._obs = self.env.reset(seed=seed * 7919 + worker_index)
        # Per-env episode-return accounting for metrics.
        self._ep_return = np.zeros(self.env.num_envs, dtype=np.float64)
        self._ep_len = np.zeros(self.env.num_envs, dtype=np.int64)
        self._completed_returns: list[float] = []
        self._completed_lengths: list[int] = []

        fwd = (self.module.forward_exploration if explore
               else self.module.forward_inference)

        def policy_step(params, obs, seed):
            rng = jax.random.fold_in(
                jax.random.PRNGKey(self._seed_base), seed)
            # "t" doubles as the exploration-schedule clock (e.g. DQN's
            # epsilon decay); traced, so no retrace as it changes.
            return fwd(params, {"obs": obs, "t": seed}, rng)

        jitted = jax.jit(policy_step)
        if self._device is not None:
            device = self._device

            def policy_on_device(params, obs, rng):
                with jax.default_device(device):
                    return jitted(params, obs, rng)

            self._policy_step = policy_on_device
        else:
            self._policy_step = jitted

    # -- weights sync ------------------------------------------------
    def set_weights(self, weights, version: int = 0) -> None:
        self._weights = weights
        self._weights_version = version

    def get_weights_version(self) -> int:
        return self._weights_version

    # -- sampling ----------------------------------------------------
    def sample(self, num_steps: int | None = None) -> SampleBatch:
        """Collect a [T, B] fragment. Hot loop: one vectorized env step +
        one jitted policy call per T."""
        assert self._weights is not None, "set_weights() before sample()"
        T = num_steps or self.rollout_fragment_length
        B = self.env.num_envs
        cols: dict[str, list] = {k: [] for k in (
            Columns.OBS, Columns.ACTIONS, Columns.REWARDS,
            Columns.TERMINATEDS, Columns.TRUNCATEDS, Columns.ACTION_LOGP,
            Columns.VF_PREDS, Columns.ACTION_LOGITS)}

        obs = self._obs
        for _ in range(T):
            self._step_counter += 1
            out = self._policy_step(self._weights, obs,
                                    self._step_counter)
            actions = np.asarray(out["actions"])
            next_obs, rewards, term, trunc = self.env.step(actions)

            cols[Columns.OBS].append(obs)
            cols[Columns.ACTIONS].append(actions)
            cols[Columns.REWARDS].append(rewards)
            cols[Columns.TERMINATEDS].append(term)
            cols[Columns.TRUNCATEDS].append(trunc)
            cols[Columns.ACTION_LOGP].append(
                np.asarray(out.get("action_logp", np.zeros(B))))
            cols[Columns.VF_PREDS].append(
                np.asarray(out.get("vf_preds", np.zeros(B))))
            cols[Columns.ACTION_LOGITS].append(
                np.asarray(out["action_logits"]))

            self._ep_return += rewards
            self._ep_len += 1
            done = term | trunc
            if done.any():
                for i in np.flatnonzero(done):
                    self._completed_returns.append(float(self._ep_return[i]))
                    self._completed_lengths.append(int(self._ep_len[i]))
                self._ep_return[done] = 0.0
                self._ep_len[done] = 0
            obs = next_obs

        self._obs = obs
        batch = SampleBatch(
            {k: np.stack(v, axis=0) for k, v in cols.items()})
        # Bootstrap values for the final obs of each env lane: one more
        # policy call on the current obs.
        self._step_counter += 1
        out = self._policy_step(self._weights, obs, self._step_counter)
        batch["bootstrap_value"] = np.asarray(out.get(
            "vf_preds", np.zeros(B)))
        batch["weights_version"] = np.full(
            (batch[Columns.OBS].shape[0],), self._weights_version,
            dtype=np.int64)
        return batch

    def get_metrics(self) -> dict:
        """Drain episode metrics (reference: env runner metrics logger)."""
        rets, lens = self._completed_returns, self._completed_lengths
        self._completed_returns, self._completed_lengths = [], []
        if not rets:
            return {"num_episodes": 0}
        return {
            "num_episodes": len(rets),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def ping(self) -> str:
        return "pong"
