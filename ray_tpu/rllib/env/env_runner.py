"""EnvRunner — rollout collection actors.

Reference: rllib/env/env_runner.py (EnvRunner API) and
single_agent_env_runner.py:27/:125 (SingleAgentEnvRunner.sample — the
rollout hot loop). Design differences for TPU:

- envs are stepped as a batched vector env (numpy), so the policy
  forward is ONE jitted call over [B, obs] per env step — the classic
  per-env Python loop never appears;
- the runner keeps module params as a host-local pytree; inference runs
  on whatever backend jit picks (CPU for rollout actors, so the TPU
  stays dedicated to the learner);
- output is a time-major SampleBatch fragment [T, B], which is exactly
  the layout GAE/V-trace scans want — no transpose on the learner.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.runner_common import (
    EpisodeStats,
    make_policy_step,
    rollout_device,
    worker_seed_base,
)
from ray_tpu.rllib.env.vector_env import make_vector_env
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class SingleAgentEnvRunner:
    """Collects fixed-length rollout fragments from a vector env."""

    def __init__(self, *, env_id: str, module_spec: RLModuleSpec,
                 num_envs: int = 8, rollout_fragment_length: int = 64,
                 seed: int = 0, worker_index: int = 0,
                 explore: bool = True, inference_backend: str = "cpu"):
        self.worker_index = worker_index
        # Rollout inference defaults to the CPU backend: per-step policy
        # calls are tiny and latency-bound, and pinning them to CPU keeps
        # the TPU dedicated to the learner (the reference gets this for
        # free because env runners are plain CPU actors).
        self._device = rollout_device(inference_backend)
        self.env = make_vector_env(env_id, num_envs)
        self.module = module_spec.build()
        self.rollout_fragment_length = rollout_fragment_length
        self.explore = explore
        self._seed_base = worker_seed_base(seed, worker_index)
        self._step_counter = 0
        self._weights = None
        self._weights_version = -1
        self._obs = self.env.reset(seed=seed * 7919 + worker_index)
        self._stats = EpisodeStats(self.env.num_envs)

        fwd = (self.module.forward_exploration if explore
               else self.module.forward_inference)
        self._policy_step = make_policy_step(
            fwd, self._seed_base, self._device)

    # -- weights sync ------------------------------------------------
    def set_weights(self, weights, version: int = 0) -> None:
        # Commit once to the rollout device: host-numpy params would be
        # re-uploaded on EVERY jitted policy call (T transfers per
        # fragment instead of one per sync).
        try:
            weights = (jax.device_put(weights, self._device)
                       if self._device is not None
                       else jax.device_put(weights))
        except Exception:  # noqa: BLE001 — keep host copy on odd backends
            pass
        self._weights = weights
        self._weights_version = version

    def get_weights_version(self) -> int:
        return self._weights_version

    # -- sampling ----------------------------------------------------
    def sample(self, num_steps: int | None = None) -> SampleBatch:
        """Collect a [T, B] fragment. Hot loop: one vectorized env step +
        one jitted policy call per T."""
        assert self._weights is not None, "set_weights() before sample()"
        T = num_steps or self.rollout_fragment_length
        B = self.env.num_envs
        cols: dict[str, list] = {k: [] for k in (
            Columns.OBS, Columns.ACTIONS, Columns.REWARDS,
            Columns.TERMINATEDS, Columns.TRUNCATEDS, Columns.ACTION_LOGP,
            Columns.VF_PREDS, Columns.ACTION_LOGITS)}

        obs = self._obs
        for _ in range(T):
            self._step_counter += 1
            out = self._policy_step(self._weights, obs,
                                    self._step_counter)
            actions = np.asarray(out["actions"])
            next_obs, rewards, term, trunc = self.env.step(actions)

            cols[Columns.OBS].append(obs)
            cols[Columns.ACTIONS].append(actions)
            cols[Columns.REWARDS].append(rewards)
            cols[Columns.TERMINATEDS].append(term)
            cols[Columns.TRUNCATEDS].append(trunc)
            cols[Columns.ACTION_LOGP].append(
                np.asarray(out.get("action_logp", np.zeros(B))))
            cols[Columns.VF_PREDS].append(
                np.asarray(out.get("vf_preds", np.zeros(B))))
            cols[Columns.ACTION_LOGITS].append(
                np.asarray(out["action_logits"]))

            self._stats.record(rewards, term, trunc)
            obs = next_obs

        self._obs = obs
        batch = SampleBatch(
            {k: np.stack(v, axis=0) for k, v in cols.items()})
        # Bootstrap values for the final obs of each env lane: one more
        # policy call on the current obs.
        self._step_counter += 1
        out = self._policy_step(self._weights, obs, self._step_counter)
        batch["bootstrap_value"] = np.asarray(out.get(
            "vf_preds", np.zeros(B)))
        batch["weights_version"] = np.full(
            (batch[Columns.OBS].shape[0],), self._weights_version,
            dtype=np.int64)
        return batch

    def get_metrics(self) -> dict:
        """Drain episode metrics (reference: env runner metrics logger)."""
        return self._stats.drain()

    def ping(self) -> str:
        return "pong"
