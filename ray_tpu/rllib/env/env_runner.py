"""EnvRunner — rollout collection actors.

Reference: rllib/env/env_runner.py (EnvRunner API) and
single_agent_env_runner.py:27/:125 (SingleAgentEnvRunner.sample — the
rollout hot loop). Design differences for TPU:

- envs are stepped as a batched vector env (numpy), so the policy
  forward is ONE jitted call over [B, obs] per env step — the classic
  per-env Python loop never appears;
- the runner keeps module params as a host-local pytree; inference runs
  on whatever backend jit picks (CPU for rollout actors, so the TPU
  stays dedicated to the learner);
- output is a time-major SampleBatch fragment [T, B], which is exactly
  the layout GAE/V-trace scans want — no transpose on the learner.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.runner_common import (
    EpisodeStats,
    make_policy_step,
    rollout_device,
    worker_seed_base,
)
from ray_tpu.rllib.env.vector_env import make_vector_env
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class SingleAgentEnvRunner:
    """Collects fixed-length rollout fragments from a vector env."""

    def __init__(self, *, env_id: str, module_spec: RLModuleSpec,
                 num_envs: int = 8, rollout_fragment_length: int = 64,
                 seed: int = 0, worker_index: int = 0,
                 explore: bool = True, inference_backend: str = "cpu",
                 fused_rollouts: bool | None = None,
                 emit_columns: tuple | None = None):
        from ray_tpu.rllib.env.jax_env import get_jax_env

        self.worker_index = worker_index
        # Rollout inference defaults to the CPU backend: per-step policy
        # calls are tiny and latency-bound, and pinning them to CPU keeps
        # the TPU dedicated to the learner (the reference gets this for
        # free because env runners are plain CPU actors).
        self._device = rollout_device(inference_backend)
        self.module = module_spec.build()
        self.rollout_fragment_length = rollout_fragment_length
        self.explore = explore
        self._seed_base = worker_seed_base(seed, worker_index)
        self._step_counter = 0
        self._weights = None
        self._weights_version = -1
        # Consumers that don't need every column skip its transport
        # (IMPALA recomputes values/logits in the learner; shipping them
        # wastes a third of the batch bytes).
        self._emit_columns = (set(emit_columns)
                              if emit_columns is not None else None)

        # Device-resident rollouts: when the env has a pure-JAX
        # implementation, the whole fragment (policy + physics +
        # auto-reset) is ONE jitted lax.scan — no per-step dispatch
        # (jax_env.py; no reference equivalent, rllib steps envs from
        # Python per step). Default: on for accelerator rollout devices
        # (dispatch-bound, the scan wins); off on CPU, where XLA's
        # while-loop overhead per tiny step loses to the vectorized
        # numpy loop — measured, not assumed.
        # Recurrent modules thread state through the per-step loop;
        # the fused scan has no state plumbing (yet), so they always
        # take the step-loop path.
        self._recurrent = bool(getattr(self.module, "is_recurrent",
                                       False))
        if fused_rollouts is None:
            fused_rollouts = (self._device is not None
                              and self._device.platform != "cpu")
        if self._recurrent:
            fused_rollouts = False
        self._jax_env = get_jax_env(env_id, num_envs) \
            if fused_rollouts else None
        if self._jax_env is not None:
            self.env = self._jax_env  # exposes num_envs/spaces
            reset_rng = jax.random.PRNGKey(
                np.uint32(seed * 7919 + worker_index))
            self._env_state, self._obs = self._jax_env.reset(reset_rng)
            self._fused_fns: dict[int, Any] = {}
        else:
            self.env = make_vector_env(env_id, num_envs)
            self._obs = self.env.reset(seed=seed * 7919 + worker_index)
        self._stats = EpisodeStats(self.env.num_envs)

        fwd = (self.module.forward_exploration if explore
               else self.module.forward_inference)
        self._fwd = fwd
        if self._recurrent:
            from ray_tpu.rllib.env.runner_common import (
                make_recurrent_policy_step,
            )

            self._rnn_state = np.asarray(
                self.module.initial_state(self.env.num_envs))
            recurrent_step = make_recurrent_policy_step(
                fwd, self._seed_base, self._device)
            # One call shape for both module kinds: the recurrent
            # variant reads the CURRENT state at call time.
            self._policy_step = (
                lambda w, o, t: recurrent_step(w, o, self._rnn_state, t))
        else:
            self._policy_step = make_policy_step(
                fwd, self._seed_base, self._device)

    # -- weights sync ------------------------------------------------
    def set_weights(self, weights, version: int = 0) -> None:
        # Commit once to the rollout device: host-numpy params would be
        # re-uploaded on EVERY jitted policy call (T transfers per
        # fragment instead of one per sync).
        try:
            weights = (jax.device_put(weights, self._device)
                       if self._device is not None
                       else jax.device_put(weights))
        except Exception:  # noqa: BLE001 — keep host copy on odd backends
            pass
        self._weights = weights
        self._weights_version = version

    def get_weights_version(self) -> int:
        return self._weights_version

    # -- sampling ----------------------------------------------------
    def _fused_rollout_fn(self, T: int):
        """One jitted fn per fragment length: lax.scan over T of
        (policy forward -> env.step), bootstrap value included."""
        cached = self._fused_fns.get(T)
        if cached is not None:
            return cached
        env = self._jax_env
        fwd = self._fwd
        seed_base = self._seed_base
        emit = self._emit_columns
        import jax.numpy as jnp

        def rollout(weights, env_state, obs, start_t):
            base = jax.random.PRNGKey(seed_base)

            def body(carry, i):
                env_state, obs = carry
                rng = jax.random.fold_in(base, start_t + i)
                out = fwd(weights, {"obs": obs, "t": start_t + i}, rng)
                actions = out["actions"]
                # step_final: the TRUE successor obs (pre-auto-reset)
                # rides along so fused fragments carry the same
                # next_obs column — and semantics — as the step loop.
                env_state, next_obs, rew, term, trunc, final = \
                    env.step_final(env_state, actions)
                ys = {Columns.OBS: obs, Columns.ACTIONS: actions,
                      Columns.REWARDS: rew, Columns.TERMINATEDS: term,
                      Columns.TRUNCATEDS: trunc}
                if emit is None or Columns.NEXT_OBS in emit:
                    ys[Columns.NEXT_OBS] = final
                # Filtered columns never enter the scan's stacked
                # outputs, so their device->host transfer is never paid.
                for key, value in (
                        (Columns.ACTION_LOGP,
                         out.get("action_logp", jnp.zeros_like(rew))),
                        (Columns.VF_PREDS,
                         out.get("vf_preds", jnp.zeros_like(rew))),
                        (Columns.ACTION_LOGITS, out["action_logits"])):
                    if emit is None or key in emit:
                        ys[key] = value
                return (env_state, next_obs), ys

            (env_state, obs), ys = jax.lax.scan(
                body, (env_state, obs), jnp.arange(T))
            brng = jax.random.fold_in(base, start_t + T)
            bout = fwd(weights, {"obs": obs, "t": start_t + T}, brng)
            bootstrap = bout.get("vf_preds", jnp.zeros(obs.shape[0]))
            return env_state, obs, ys, bootstrap

        jitted = jax.jit(rollout)
        if self._device is not None:
            def on_device(*args, _jitted=jitted):
                with jax.default_device(self._device):
                    return _jitted(*args)
            fn = on_device
        else:
            fn = jitted
        self._fused_fns[T] = fn
        return fn

    def _sample_fused(self, T: int) -> SampleBatch:
        fn = self._fused_rollout_fn(T)
        self._env_state, self._obs, ys, bootstrap = fn(
            self._weights, self._env_state, self._obs,
            self._step_counter)
        self._step_counter += T + 1
        batch = SampleBatch(jax.device_get(ys))
        batch["bootstrap_value"] = np.asarray(bootstrap)
        batch["weights_version"] = np.full(
            (T,), self._weights_version, dtype=np.int64)
        self._stats.record_fragment(
            batch[Columns.REWARDS], batch[Columns.TERMINATEDS],
            batch[Columns.TRUNCATEDS])
        return batch

    _OPTIONAL_COLUMNS = (Columns.ACTION_LOGP, Columns.VF_PREDS,
                         Columns.ACTION_LOGITS, Columns.NEXT_OBS)

    def _filter_columns(self, batch: SampleBatch) -> SampleBatch:
        if self._emit_columns is None:
            return batch
        for key in self._OPTIONAL_COLUMNS:
            if key not in self._emit_columns:
                batch.pop(key, None)
        return batch

    def sample(self, num_steps: int | None = None) -> SampleBatch:
        """Collect a [T, B] fragment. Fused path: ONE jitted scan for
        the whole fragment; fallback: one vectorized env step + one
        jitted policy call per T."""
        assert self._weights is not None, "set_weights() before sample()"
        T = num_steps or self.rollout_fragment_length
        if self._jax_env is not None:
            return self._sample_fused(T)
        B = self.env.num_envs
        cols: dict[str, list] = {k: [] for k in (
            Columns.OBS, Columns.ACTIONS, Columns.REWARDS,
            Columns.TERMINATEDS, Columns.TRUNCATEDS, Columns.ACTION_LOGP,
            Columns.VF_PREDS, Columns.ACTION_LOGITS, Columns.NEXT_OBS)}

        state_in = (self._rnn_state.copy() if self._recurrent
                    else None)
        obs = self._obs
        for _ in range(T):
            self._step_counter += 1
            out = self._policy_step(self._weights, obs,
                                    self._step_counter)
            actions = np.asarray(out["actions"])
            next_obs, rewards, term, trunc = self.env.step(actions)
            if self._recurrent:
                # Thread state; episode boundaries reset their lanes
                # (the env auto-resets, so the next obs starts a NEW
                # episode whose state must be the initial one).
                state = np.asarray(out["state_out"])
                done = term | trunc
                if done.any():
                    state = state.copy()
                    state[done] = np.asarray(
                        self.module.initial_state(int(done.sum())))
                self._rnn_state = state

            cols[Columns.OBS].append(obs)
            # TRUE successor observation: at terminated/truncated steps
            # the env's returned obs is the NEXT episode's reset obs —
            # final_obs carries the pre-reset one, which is what
            # V(next_obs) bootstrap and offline logs must see.
            final = getattr(self.env, "final_obs", None)
            cols[Columns.NEXT_OBS].append(
                next_obs if final is None else final)
            cols[Columns.ACTIONS].append(actions)
            cols[Columns.REWARDS].append(rewards)
            cols[Columns.TERMINATEDS].append(term)
            cols[Columns.TRUNCATEDS].append(trunc)
            cols[Columns.ACTION_LOGP].append(
                np.asarray(out.get("action_logp", np.zeros(B))))
            cols[Columns.VF_PREDS].append(
                np.asarray(out.get("vf_preds", np.zeros(B))))
            cols[Columns.ACTION_LOGITS].append(
                np.asarray(out["action_logits"]))

            self._stats.record(rewards, term, trunc)
            obs = next_obs

        self._obs = obs
        batch = SampleBatch(
            {k: np.stack(v, axis=0) for k, v in cols.items()})
        if state_in is not None:
            # The fragment's INITIAL recurrent state, [B, ...]: the
            # learner unrolls from here (reference: R2D2 stores the
            # recurrent state with each sequence).
            batch["state_in"] = state_in
        # Bootstrap values for the final obs of each env lane: one more
        # policy call on the current obs.
        self._step_counter += 1
        out = self._policy_step(self._weights, obs, self._step_counter)
        batch["bootstrap_value"] = np.asarray(out.get(
            "vf_preds", np.zeros(B)))
        batch["weights_version"] = np.full(
            (batch[Columns.OBS].shape[0],), self._weights_version,
            dtype=np.int64)
        return self._filter_columns(batch)

    def get_metrics(self) -> dict:
        """Drain episode metrics (reference: env runner metrics logger)."""
        return self._stats.drain()

    def ping(self) -> str:
        return "pong"
