"""Multi-agent environments.

Reference: rllib/env/multi_agent_env.py (MultiAgentEnv: dict-keyed
obs/rewards/dones per agent; `make_multi_agent`:378 turns any
single-agent env into an N-agent copy env). TPU-first shape: the
multi-agent env is *vectorized* like everything else — each agent
contributes a [B, obs] block per step, so a policy serving K agents
runs ONE jitted forward over [K*B, obs] instead of K per-agent calls.

Simplification vs the reference: agents are fixed for the env's
lifetime and all act every step (lockstep); per-agent episode
boundaries are still independent (each agent's lanes auto-reset on its
own done). Turn-based games can encode "not my turn" as a no-op action.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.env.vector_env import VectorEnv, make_vector_env


class MultiAgentVectorEnv:
    """B lockstep copies of an N-agent environment.

    Dict-keyed API (reference MultiAgentEnv):
      reset(seed)            -> {agent_id: [B, obs]}
      step({agent_id: [B]})  -> (obs, rewards, terminateds, truncateds)
                                 each {agent_id: [B]-shaped arrays}
    """

    num_envs: int
    agent_ids: tuple

    def observation_size(self, agent_id: str) -> int:
        raise NotImplementedError

    def num_actions(self, agent_id: str) -> int:
        raise NotImplementedError

    def action_size(self, agent_id: str) -> int:
        return 0

    def reset(self, seed: int | None = None) -> dict:
        raise NotImplementedError

    def step(self, actions: dict):
        raise NotImplementedError


class IndependentMultiAgentEnv(MultiAgentVectorEnv):
    """N agents each driving an independent copy of a single-agent env
    (reference: make_multi_agent, multi_agent_env.py:378 — the standard
    multi-agent CartPole used across rllib's test suite)."""

    def __init__(self, env_id: str, num_agents: int = 2,
                 num_envs: int = 8):
        self.num_envs = num_envs
        self.agent_ids = tuple(f"agent_{i}" for i in range(num_agents))
        self._envs = {aid: make_vector_env(env_id, num_envs)
                      for aid in self.agent_ids}

    def observation_size(self, agent_id: str) -> int:
        return self._envs[agent_id].observation_size

    def num_actions(self, agent_id: str) -> int:
        return self._envs[agent_id].num_actions

    def action_size(self, agent_id: str) -> int:
        return getattr(self._envs[agent_id], "action_size", 0)

    def reset(self, seed: int | None = None) -> dict:
        return {aid: env.reset(None if seed is None else seed + i)
                for i, (aid, env) in enumerate(self._envs.items())}

    def step(self, actions: dict):
        obs, rew, term, trunc = {}, {}, {}, {}
        for aid, env in self._envs.items():
            obs[aid], rew[aid], term[aid], trunc[aid] = env.step(
                actions[aid])
        return obs, rew, term, trunc


def make_multi_agent(env_id: str):
    """Factory-of-factories (reference multi_agent_env.py:378):
    ``MultiCartPole = make_multi_agent("CartPole-v1")``,
    ``env = MultiCartPole(num_agents=4, num_envs=8)``."""

    def factory(num_agents: int = 2, num_envs: int = 8):
        return IndependentMultiAgentEnv(env_id, num_agents, num_envs)

    return factory


_MULTI_BUILTIN: dict = {}


def register_multi_agent_env(env_id: str, factory) -> None:
    _MULTI_BUILTIN[env_id] = factory


def make_multi_agent_env(env_id: str, num_agents: int,
                         num_envs: int) -> MultiAgentVectorEnv:
    if env_id in _MULTI_BUILTIN:
        return _MULTI_BUILTIN[env_id](num_agents=num_agents,
                                      num_envs=num_envs)
    # Fall back to N independent copies of a (builtin or gym) env.
    return IndependentMultiAgentEnv(env_id, num_agents, num_envs)
