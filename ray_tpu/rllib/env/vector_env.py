"""Vectorized environments for rollout actors.

Reference: rllib's EnvRunner wraps gymnasium vector envs
(rllib/env/single_agent_env_runner.py:27). Here the built-in envs are
pure-numpy batched implementations — the rollout hot loop steps B envs
in one vectorized call with no per-env Python loop, which is what feeds
a jitted batched policy efficiently. gymnasium envs are supported via
``gym_vector_env`` when a non-builtin id is requested.
"""

from __future__ import annotations

import numpy as np


class VectorEnv:
    """B independent env copies stepped in lockstep (auto-reset on done)."""

    num_envs: int
    observation_size: int
    num_actions: int      # discrete envs; 0 for continuous
    action_size: int = 0  # continuous envs; 0 for discrete
    # Continuous action bounds (symmetric box, one scalar for all dims).
    action_scale: float = 1.0
    # The TRUE post-step observation of the last step(), BEFORE any
    # auto-reset ([B, obs]); equals the returned obs for non-done lanes.
    # Consumers that record transitions (offline writers, replay) need
    # the real successor at terminated/truncated steps — the returned
    # obs there is the NEXT episode's reset obs (reference: gymnasium's
    # final_observation info of autoreset vector envs).
    final_obs: np.ndarray | None = None

    def reset(self, seed: int | None = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray):
        """-> (obs, rewards, terminateds, truncateds). Auto-resets done
        envs; the returned obs for a done env is the fresh reset obs
        (the pre-reset one is kept in ``final_obs``)."""
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """Batched CartPole-v1 (classic control; standard physics constants).

    Matches gymnasium's CartPole-v1 dynamics and termination thresholds
    so learning curves are comparable; 500-step truncation.
    """

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5  # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    observation_size = 4
    num_actions = 2

    def __init__(self, num_envs: int = 8, max_steps: int | None = None):
        self.num_envs = num_envs
        self.max_steps = max_steps or self.MAX_STEPS
        self._state = np.zeros((num_envs, 4), dtype=np.float64)
        self._t = np.zeros(num_envs, dtype=np.int64)
        self._rng = np.random.default_rng(0)

    def _sample_state(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4))

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._sample_state(self.num_envs)
        self._t[:] = 0
        return self._state.astype(np.float32)

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._t += 1

        terminated = ((np.abs(x) > self.X_LIMIT)
                      | (np.abs(theta) > self.THETA_LIMIT))
        truncated = (~terminated) & (self._t >= self.max_steps)
        rewards = np.ones(self.num_envs, dtype=np.float32)

        done = terminated | truncated
        self.final_obs = self._state.astype(np.float32)
        if done.any():
            self._state[done] = self._sample_state(int(done.sum()))
            self._t[done] = 0
        return (self._state.astype(np.float32), rewards,
                terminated, truncated)


class PendulumVectorEnv(VectorEnv):
    """Batched Pendulum-v1 (continuous torque control).

    Matches gymnasium's Pendulum-v1 dynamics (g=10, m=1, l=1, dt=0.05,
    torque clip ±2, speed clip ±8) so SAC learning curves are
    comparable; 200-step truncation, never terminates.
    """

    G = 10.0
    M = 1.0
    L = 1.0
    DT = 0.05
    MAX_TORQUE = 2.0
    MAX_SPEED = 8.0
    MAX_STEPS = 200

    observation_size = 3
    num_actions = 0
    action_size = 1
    action_scale = 2.0  # torque range ±2

    def __init__(self, num_envs: int = 8, max_steps: int | None = None):
        self.num_envs = num_envs
        self.max_steps = max_steps or self.MAX_STEPS
        self._theta = np.zeros(num_envs)
        self._thetadot = np.zeros(num_envs)
        self._t = np.zeros(num_envs, dtype=np.int64)
        self._rng = np.random.default_rng(0)

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._theta), np.sin(self._theta),
                         self._thetadot], axis=1).astype(np.float32)

    def _sample_state(self, n: int):
        return (self._rng.uniform(-np.pi, np.pi, size=n),
                self._rng.uniform(-1.0, 1.0, size=n))

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._theta, self._thetadot = self._sample_state(self.num_envs)
        self._t[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, dtype=np.float64).reshape(-1),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        theta, thetadot = self._theta, self._thetadot
        angle_norm = ((theta + np.pi) % (2 * np.pi)) - np.pi
        costs = angle_norm**2 + 0.1 * thetadot**2 + 0.001 * u**2

        thetadot = thetadot + self.DT * (
            3 * self.G / (2 * self.L) * np.sin(theta)
            + 3.0 / (self.M * self.L**2) * u)
        thetadot = np.clip(thetadot, -self.MAX_SPEED, self.MAX_SPEED)
        theta = theta + self.DT * thetadot
        self._theta, self._thetadot = theta, thetadot
        self._t += 1

        terminated = np.zeros(self.num_envs, dtype=bool)
        truncated = self._t >= self.max_steps
        self.final_obs = self._obs()
        if truncated.any():
            n = int(truncated.sum())
            new_theta, new_thetadot = self._sample_state(n)
            self._theta[truncated] = new_theta
            self._thetadot[truncated] = new_thetadot
            self._t[truncated] = 0
        return (self._obs(), (-costs).astype(np.float32),
                terminated, truncated)


class GymVectorEnv(VectorEnv):
    """Adapter over gymnasium.vector.SyncVectorEnv for non-builtin ids."""

    def __init__(self, env_id: str, num_envs: int = 8):
        import gymnasium as gym

        self.num_envs = num_envs
        self._env = gym.vector.SyncVectorEnv(
            [lambda: gym.make(env_id) for _ in range(num_envs)])
        space = self._env.single_observation_space
        self.observation_size = int(np.prod(space.shape))
        act_space = self._env.single_action_space
        if hasattr(act_space, "n"):           # Discrete
            self.num_actions = int(act_space.n)
            self.action_size = 0
        else:                                  # Box (continuous)
            self.num_actions = 0
            self.action_size = int(np.prod(act_space.shape))
            self.action_scale = float(np.max(np.abs(act_space.high)))

    def reset(self, seed: int | None = None) -> np.ndarray:
        obs, _ = self._env.reset(seed=seed)
        return obs.reshape(self.num_envs, -1).astype(np.float32)

    def step(self, actions: np.ndarray):
        obs, rewards, term, trunc, infos = self._env.step(
            np.asarray(actions))
        flat = obs.reshape(self.num_envs, -1).astype(np.float32)
        # gymnasium autoreset: the pre-reset observation of done lanes
        # rides infos["final_observation"] (older API: "final_obs").
        self.final_obs = flat.copy()
        finals = infos.get("final_observation",
                           infos.get("final_obs")) \
            if isinstance(infos, dict) else None
        if finals is not None:
            for i, f in enumerate(finals):
                if f is not None:
                    self.final_obs[i] = np.asarray(
                        f, dtype=np.float32).reshape(-1)
        return (flat, rewards.astype(np.float32), term, trunc)


_BUILTIN = {"CartPole-v1": CartPoleVectorEnv,
            "Pendulum-v1": PendulumVectorEnv}


def make_vector_env(env_id: str, num_envs: int) -> VectorEnv:
    if env_id in _BUILTIN:
        return _BUILTIN[env_id](num_envs)
    return GymVectorEnv(env_id, num_envs)


def register_env(env_id: str, factory) -> None:
    """Register a VectorEnv factory (reference: ray.tune.register_env)."""
    _BUILTIN[env_id] = factory
