"""MultiAgentEnvRunner — rollout collection over multi-agent envs.

Reference: rllib/env/multi_agent_env_runner.py (MultiAgentEnvRunner:
steps a MultiAgentEnv, routes per-agent obs through policy_mapping_fn
to modules, emits MultiAgentEpisodes). TPU shape: per step there is ONE
jitted policy call per *policy* (not per agent) — agents mapped to the
same policy have their [B, obs] blocks concatenated into a single
[K*B, obs] forward, then actions are split back per agent. Fragments
come out as {policy_id: SampleBatch[T, K*B]} — already merged along the
batch axis, so the learner consumes them with zero reshuffling.
"""

from __future__ import annotations

from typing import Callable

import jax
import numpy as np

from ray_tpu.rllib.core.multi_rl_module import MultiRLModuleSpec
from ray_tpu.rllib.env.multi_agent_env import make_multi_agent_env
from ray_tpu.rllib.env.runner_common import (
    EpisodeStats,
    make_policy_step,
    rollout_device,
    worker_seed_base,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class MultiAgentEnvRunner:
    """Collects {policy_id: [T, K*B]} fragments."""

    def __init__(self, *, env_id: str, marl_spec: MultiRLModuleSpec,
                 policy_mapping_fn: Callable[[str], str],
                 num_agents: int = 2, num_envs: int = 8,
                 rollout_fragment_length: int = 64, seed: int = 0,
                 worker_index: int = 0, explore: bool = True,
                 inference_backend: str = "cpu"):
        self.worker_index = worker_index
        self._device = rollout_device(inference_backend)
        self.env = make_multi_agent_env(env_id, num_agents, num_envs)
        self.marl_module = marl_spec.build()
        self.policy_mapping_fn = policy_mapping_fn
        self.rollout_fragment_length = rollout_fragment_length
        self.explore = explore
        # policy_id -> ordered agent list (order fixes the concat layout).
        self.policy_agents: dict[str, list[str]] = {}
        for aid in self.env.agent_ids:
            pid = policy_mapping_fn(aid)
            if pid not in self.marl_module:
                raise KeyError(
                    f"policy_mapping_fn({aid!r}) = {pid!r} which is not "
                    f"in the MultiRLModuleSpec ({list(self.marl_module.keys())})")
            self.policy_agents.setdefault(pid, []).append(aid)

        self._seed_base = worker_seed_base(seed, worker_index)
        self._step_counter = 0
        self._weights: dict | None = None
        self._weights_version = -1
        self._obs = self.env.reset(seed=seed * 7919 + worker_index)
        B = self.env.num_envs
        self._stats = {aid: EpisodeStats(B) for aid in self.env.agent_ids}

        # One jitted policy step per policy.
        self._policy_steps = {}
        for pid in self.policy_agents:
            module = self.marl_module[pid]
            fwd = (module.forward_exploration if explore
                   else module.forward_inference)
            self._policy_steps[pid] = make_policy_step(
                fwd, self._seed_base, self._device)

    # -- weights sync ------------------------------------------------
    def set_weights(self, weights: dict, version: int = 0) -> None:
        """weights: {policy_id: params pytree}."""
        self._weights = weights
        self._weights_version = version

    def get_weights_version(self) -> int:
        return self._weights_version

    # -- sampling ----------------------------------------------------
    def sample(self, num_steps: int | None = None) -> dict:
        """-> {policy_id: SampleBatch [T, K*B]} (+ bootstrap_value)."""
        assert self._weights is not None, "set_weights() before sample()"
        T = num_steps or self.rollout_fragment_length
        B = self.env.num_envs
        keys = (Columns.OBS, Columns.ACTIONS, Columns.REWARDS,
                Columns.TERMINATEDS, Columns.TRUNCATEDS,
                Columns.ACTION_LOGP, Columns.VF_PREDS,
                Columns.ACTION_LOGITS)
        cols = {pid: {k: [] for k in keys} for pid in self.policy_agents}

        obs = self._obs
        for _ in range(T):
            self._step_counter += 1
            actions, per_policy_out = self._act(obs)
            next_obs, rewards, term, trunc = self.env.step(actions)

            for pid, agents in self.policy_agents.items():
                out = per_policy_out[pid]
                c = cols[pid]
                c[Columns.OBS].append(
                    np.concatenate([obs[a] for a in agents], axis=0))
                c[Columns.ACTIONS].append(np.asarray(out["actions"]))
                c[Columns.REWARDS].append(
                    np.concatenate([rewards[a] for a in agents], axis=0))
                c[Columns.TERMINATEDS].append(
                    np.concatenate([term[a] for a in agents], axis=0))
                c[Columns.TRUNCATEDS].append(
                    np.concatenate([trunc[a] for a in agents], axis=0))
                n = len(agents) * B
                c[Columns.ACTION_LOGP].append(np.asarray(
                    out.get("action_logp", np.zeros(n))))
                c[Columns.VF_PREDS].append(np.asarray(
                    out.get("vf_preds", np.zeros(n))))
                c[Columns.ACTION_LOGITS].append(
                    np.asarray(out["action_logits"]))

            for aid in self.env.agent_ids:
                self._stats[aid].record(rewards[aid], term[aid], trunc[aid])
            obs = next_obs

        self._obs = obs
        fragments = {}
        self._step_counter += 1
        _, bootstrap_out = self._act(obs)
        for pid in self.policy_agents:
            batch = SampleBatch(
                {k: np.stack(v, axis=0) for k, v in cols[pid].items()})
            n = len(self.policy_agents[pid]) * B
            batch["bootstrap_value"] = np.asarray(
                bootstrap_out[pid].get("vf_preds", np.zeros(n)))
            batch["weights_version"] = np.full(
                (T,), self._weights_version, dtype=np.int64)
            fragments[pid] = batch
        return fragments

    def _act(self, obs: dict):
        """One jitted forward per policy over concatenated agent blocks;
        returns (per-agent action dict, per-policy raw outputs)."""
        B = self.env.num_envs
        actions: dict = {}
        per_policy_out: dict = {}
        for pid, agents in self.policy_agents.items():
            stacked = np.concatenate([obs[a] for a in agents], axis=0)
            out = self._policy_steps[pid](
                self._weights[pid], stacked, self._step_counter)
            per_policy_out[pid] = out
            acts = np.asarray(out["actions"])
            for j, aid in enumerate(agents):
                actions[aid] = acts[j * B:(j + 1) * B]
        return actions, per_policy_out

    def get_metrics(self) -> dict:
        """Drain per-agent episode metrics, merged across agents."""
        drains = [s.drain() for s in self._stats.values()]
        n = sum(d["num_episodes"] for d in drains)
        if n == 0:
            return {"num_episodes": 0}
        means = [d["episode_return_mean"] for d in drains
                 if "episode_return_mean" in d]
        lens = [d["episode_len_mean"] for d in drains
                if "episode_len_mean" in d]
        return {
            "num_episodes": n,
            "episode_return_mean": float(np.mean(means)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def ping(self) -> str:
        return "pong"
