"""Pure-JAX vector environments — device-resident rollout dynamics.

No reference equivalent: rllib steps gymnasium envs from Python
(rllib/env/single_agent_env_runner.py:125, one Python iteration per env
step). Here the built-in control environments are pure functions of
(state, action), so the WHOLE rollout fragment — policy forward + env
physics + auto-reset — fuses into one jitted ``lax.scan``
(env_runner.py), turning T jit dispatches + T numpy steps per fragment
into a single device call. On TPU this keeps sampling on the MXU-fed
compute path; on CPU it removes the per-step dispatch overhead that
bounds IMPALA throughput.

Functional protocol: ``reset(rng) -> (state, obs)``;
``step(state, actions) -> (state, obs, reward, term, trunc)`` — state
carries the PRNG so auto-resets stay inside the scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class JaxVectorEnv:
    """B lockstep env copies as pure jittable functions."""

    num_envs: int
    observation_size: int
    num_actions: int
    action_size: int = 0
    action_scale: float = 1.0

    def reset(self, rng: jax.Array):
        raise NotImplementedError

    def step(self, state, actions):
        raise NotImplementedError

    def step_final(self, state, actions):
        """step() plus the TRUE post-step observation BEFORE any
        auto-reset (the successor obs of terminated/truncated steps —
        vector_env.VectorEnv.final_obs's jittable analogue). Default:
        the returned obs (correct for envs that never auto-reset)."""
        state, obs, rew, term, trunc = self.step(state, actions)
        return state, obs, rew, term, trunc, obs


class JaxCartPole(JaxVectorEnv):
    """CartPole-v1 dynamics as a pure function (same constants and
    termination thresholds as vector_env.CartPoleVectorEnv / gymnasium,
    so learning curves are comparable)."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * jnp.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    observation_size = 4
    num_actions = 2

    def __init__(self, num_envs: int = 8, max_steps: int | None = None):
        self.num_envs = num_envs
        self.max_steps = max_steps or self.MAX_STEPS

    def _fresh(self, rng):
        return jax.random.uniform(
            rng, (self.num_envs, 4), minval=-0.05, maxval=0.05,
            dtype=jnp.float32)

    def reset(self, rng: jax.Array):
        rng, sub = jax.random.split(rng)
        s = self._fresh(sub)
        state = {"s": s, "t": jnp.zeros(self.num_envs, jnp.int32),
                 "rng": rng}
        return state, s

    def step_final(self, state, actions):
        x, x_dot, theta, theta_dot = (state["s"][:, 0], state["s"][:, 1],
                                      state["s"][:, 2], state["s"][:, 3])
        force = jnp.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta, sintheta = jnp.cos(theta), jnp.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * theta_dot**2 * sintheta) \
            / total_mass
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0
                           - self.MASSPOLE * costheta**2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        s2 = jnp.stack([x, x_dot, theta, theta_dot], axis=1)
        t2 = state["t"] + 1

        terminated = ((jnp.abs(x) > self.X_LIMIT)
                      | (jnp.abs(theta) > self.THETA_LIMIT))
        truncated = (~terminated) & (t2 >= self.max_steps)
        rewards = jnp.ones(self.num_envs, dtype=jnp.float32)

        done = terminated | truncated
        final = s2.astype(jnp.float32)  # pre-reset successor obs
        rng, sub = jax.random.split(state["rng"])
        fresh = self._fresh(sub)
        s2 = jnp.where(done[:, None], fresh, final)
        t2 = jnp.where(done, 0, t2)
        new_state = {"s": s2, "t": t2, "rng": rng}
        return new_state, s2, rewards, terminated, truncated, final

    def step(self, state, actions):
        return self.step_final(state, actions)[:5]


class JaxPendulum(JaxVectorEnv):
    """Pendulum-v1 dynamics as a pure function (g=10, m=1, l=1,
    dt=0.05, torque clip ±2, speed clip ±8, 200-step truncation)."""

    G = 10.0
    M = 1.0
    L = 1.0
    DT = 0.05
    MAX_TORQUE = 2.0
    MAX_SPEED = 8.0
    MAX_STEPS = 200

    observation_size = 3
    num_actions = 0
    action_size = 1
    action_scale = 2.0

    def __init__(self, num_envs: int = 8, max_steps: int | None = None):
        self.num_envs = num_envs
        self.max_steps = max_steps or self.MAX_STEPS

    def _fresh(self, rng):
        r1, r2 = jax.random.split(rng)
        theta = jax.random.uniform(r1, (self.num_envs,),
                                   minval=-jnp.pi, maxval=jnp.pi)
        thetadot = jax.random.uniform(r2, (self.num_envs,),
                                      minval=-1.0, maxval=1.0)
        return theta, thetadot

    @staticmethod
    def _obs(theta, thetadot):
        return jnp.stack([jnp.cos(theta), jnp.sin(theta), thetadot],
                         axis=1).astype(jnp.float32)

    def reset(self, rng: jax.Array):
        rng, sub = jax.random.split(rng)
        theta, thetadot = self._fresh(sub)
        state = {"theta": theta, "thetadot": thetadot,
                 "t": jnp.zeros(self.num_envs, jnp.int32), "rng": rng}
        return state, self._obs(theta, thetadot)

    def step_final(self, state, actions):
        u = jnp.clip(jnp.asarray(actions, jnp.float32).reshape(-1),
                     -self.MAX_TORQUE, self.MAX_TORQUE)
        theta, thetadot = state["theta"], state["thetadot"]
        angle_norm = ((theta + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        costs = angle_norm**2 + 0.1 * thetadot**2 + 0.001 * u**2

        thetadot = thetadot + self.DT * (
            3 * self.G / (2 * self.L) * jnp.sin(theta)
            + 3.0 / (self.M * self.L**2) * u)
        thetadot = jnp.clip(thetadot, -self.MAX_SPEED, self.MAX_SPEED)
        theta = theta + self.DT * thetadot
        t2 = state["t"] + 1

        terminated = jnp.zeros(self.num_envs, dtype=bool)
        truncated = t2 >= self.max_steps
        final = self._obs(theta, thetadot)  # pre-reset successor obs
        rng, sub = jax.random.split(state["rng"])
        f_theta, f_thetadot = self._fresh(sub)
        theta = jnp.where(truncated, f_theta, theta)
        thetadot = jnp.where(truncated, f_thetadot, thetadot)
        t2 = jnp.where(truncated, 0, t2)
        new_state = {"theta": theta, "thetadot": thetadot, "t": t2,
                     "rng": rng}
        return (new_state, self._obs(theta, thetadot),
                (-costs).astype(jnp.float32), terminated, truncated,
                final)

    def step(self, state, actions):
        return self.step_final(state, actions)[:5]


_JAX_ENVS = {"CartPole-v1": JaxCartPole, "Pendulum-v1": JaxPendulum}


def get_jax_env(env_id: str, num_envs: int) -> JaxVectorEnv | None:
    """A device-resident implementation of ``env_id``, or None (the
    runner then falls back to the per-step numpy loop)."""
    cls = _JAX_ENVS.get(env_id)
    return cls(num_envs) if cls is not None else None


def register_jax_env(env_id: str, factory) -> None:
    _JAX_ENVS[env_id] = factory
