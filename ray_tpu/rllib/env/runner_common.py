"""Shared env-runner machinery (single- and multi-agent).

Reference: rllib/env/env_runner.py base-class utilities. Both runners
need the same three pieces: a deterministic per-worker seed scheme, a
jitted policy step pinned to the rollout device (CPU by default, so the
TPU stays dedicated to the learner), and per-lane episode accounting.
"""

from __future__ import annotations

import jax
import numpy as np


def worker_seed_base(seed: int, worker_index: int) -> np.uint32:
    """Deterministic per-worker PRNG base (decorrelates workers)."""
    return np.uint32((seed * 100003 + worker_index * 7919) & 0x7FFFFFFF)


def rollout_device(inference_backend: str):
    """First device of the requested backend, or None if unavailable."""
    try:
        return jax.local_devices(backend=inference_backend)[0]
    except RuntimeError:
        return None


def make_recurrent_policy_step(fwd, seed_base: np.uint32, device):
    """Recurrent variant: ``fwd(params, {"obs", "state_in", "t"}, rng)``
    — the runner threads the returned "state_out" into the next call
    (reference: RLlib's stateful RLModules carry STATE_IN/STATE_OUT
    through the connector pipeline)."""

    def policy_step(params, obs, state, seed):
        rng = jax.random.fold_in(jax.random.PRNGKey(seed_base), seed)
        return fwd(params, {"obs": obs, "state_in": state, "t": seed},
                   rng)

    jitted = jax.jit(policy_step)
    if device is None:
        return jitted

    def on_device(params, obs, state, seed):
        with jax.default_device(device):
            return jitted(params, obs, state, seed)

    return on_device


def make_policy_step(fwd, seed_base: np.uint32, device):
    """Jit ``fwd(params, {"obs", "t"}, rng)`` with the PRNG key derived
    INSIDE the jitted fn from a host integer (no device-committed key
    leaks across backends), optionally pinned to ``device``."""

    def policy_step(params, obs, seed):
        rng = jax.random.fold_in(jax.random.PRNGKey(seed_base), seed)
        # "t" doubles as the exploration-schedule clock (e.g. DQN's
        # epsilon decay); traced, so no retrace as it changes.
        return fwd(params, {"obs": obs, "t": seed}, rng)

    jitted = jax.jit(policy_step)
    if device is None:
        return jitted

    def on_device(params, obs, seed):
        with jax.default_device(device):
            return jitted(params, obs, seed)

    return on_device


class EpisodeStats:
    """Per-lane episode return/length accounting with drain semantics
    (reference: env-runner metrics logger)."""

    def __init__(self, num_lanes: int):
        self._ep_return = np.zeros(num_lanes, dtype=np.float64)
        self._ep_len = np.zeros(num_lanes, dtype=np.int64)
        self._completed_returns: list[float] = []
        self._completed_lengths: list[int] = []

    def record(self, rewards: np.ndarray, term: np.ndarray,
               trunc: np.ndarray) -> None:
        self._ep_return += rewards
        self._ep_len += 1
        done = term | trunc
        if done.any():
            for i in np.flatnonzero(done):
                self._completed_returns.append(float(self._ep_return[i]))
                self._completed_lengths.append(int(self._ep_len[i]))
            self._ep_return[done] = 0.0
            self._ep_len[done] = 0

    def record_fragment(self, rewards: np.ndarray, term: np.ndarray,
                        trunc: np.ndarray) -> None:
        """Whole-fragment [T, B] accounting in one call (the fused
        rollout path — a Python loop over T here would reintroduce the
        per-step overhead the fused path removes). Work is proportional
        to the number of COMPLETED episodes, not T."""
        T, B = rewards.shape
        done = term | trunc
        csum = np.cumsum(rewards, axis=0)
        any_done = done.any(axis=0)
        for b in np.flatnonzero(any_done):
            prev_sum = 0.0
            prev_t = -1
            base_ret = self._ep_return[b]
            base_len = self._ep_len[b]
            for t in np.flatnonzero(done[:, b]):
                self._completed_returns.append(
                    float(base_ret + csum[t, b] - prev_sum))
                self._completed_lengths.append(
                    int(base_len + (t - prev_t)))
                prev_sum = float(csum[t, b])
                prev_t = int(t)
                base_ret = 0.0
                base_len = 0
            self._ep_return[b] = csum[T - 1, b] - prev_sum
            self._ep_len[b] = (T - 1) - prev_t
        alive = ~any_done
        self._ep_return[alive] += csum[T - 1, alive]
        self._ep_len[alive] += T

    def drain(self) -> dict:
        rets, lens = self._completed_returns, self._completed_lengths
        self._completed_returns, self._completed_lengths = [], []
        if not rets:
            return {"num_episodes": 0}
        return {
            "num_episodes": len(rets),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }
