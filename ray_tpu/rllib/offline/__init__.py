"""Offline IO: log rollouts to files, read them back as datasets.

Reference: rllib/offline/ — JsonWriter/DatasetWriter log each sampled
batch as experience rows (dataset_writer.py, json_writer.py), and
DatasetReader/JsonReader feed them to the offline algorithms
(dataset_reader.py). Here both halves ride ray_tpu.data: the writer
emits parquet/json shard files any engine can read, and the reader
returns a ray_tpu.data Dataset that plugs straight into
``config.offline_data(input_=...)`` for BC/MARWIL/CQL/CRR/DT.

Row schema (one row per environment transition, episode-ordered within
each env lane):
  obs: list[float]        action-selection observation
  next_obs: list[float]   successor observation
  actions: int | list     the logged action
  rewards: float
  terminateds: bool       true terminal (resets the return accumulator)
  truncateds: bool        time-limit cut (resets WITHOUT a terminal)
  action_logp: float      behavior-policy log-prob (when sampled)
  eps_id: int             unique per (worker, lane, episode)

Usage::

    config = (PPOConfig().environment("CartPole-v1")
              .offline_output("/tmp/cartpole-out"))   # log while training
    ...
    ds = read_offline_dataset("/tmp/cartpole-out")
    bc = (BCConfig().offline_data(input_=ds) ...).build()
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

import numpy as np

from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch

__all__ = ["OfflineWriter", "read_offline_dataset"]


class OfflineWriter:
    """Shard-file experience writer (reference: json_writer.py's
    rotating output-*.json shards; parquet via pyarrow here because the
    data stack is arrow-native)."""

    def __init__(self, path: str, output_format: str = "parquet",
                 worker_index: int = 0, rows_per_file: int = 100_000):
        if output_format not in ("parquet", "json"):
            raise ValueError(
                f"output_format must be parquet|json, got "
                f"{output_format!r}")
        self.path = path
        self.format = output_format
        self.worker_index = worker_index
        self.rows_per_file = rows_per_file
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()
        self._rows: list[dict] = []
        self._file_index = 0
        self._eps_counter = 0
        # lane key (source, b) -> live episode id; episodes span
        # fragment boundaries.
        self._lane_eps: dict[tuple, int] = {}
        # lane key -> the lane's LAST step of the previous fragment,
        # awaiting its successor obs (the next fragment's obs[0]):
        # without this carry, every fragment boundary would either drop
        # a step or break the obs -> next_obs chain inside an episode.
        self._lane_carry: dict[tuple, dict] = {}

    # ------------------------------------------------------------ ingest

    def write_fragment(self, frag: SampleBatch, source: int = 0) -> int:
        """Append one [T, B] rollout fragment as transition rows.

        ``source`` distinguishes env runners: lane b of runner 0 and
        lane b of runner 1 are different environments, and stitching
        them together would chain unrelated episodes.

        Rows are emitted lane-contiguous (all of lane b's steps, in
        time order) so the offline readers' episode-segmented return
        computation sees episodes as contiguous runs ended by a
        terminated/truncated flag. Each lane's final (non-done) step is
        CARRIED until the next fragment supplies its successor obs, so
        episodes chain obs -> next_obs across fragment boundaries with
        no dropped steps."""
        obs = np.asarray(frag[Columns.OBS])
        actions = np.asarray(frag[Columns.ACTIONS])
        rewards = np.asarray(frag[Columns.REWARDS])
        terms = np.asarray(frag[Columns.TERMINATEDS])
        truncs = np.asarray(frag[Columns.TRUNCATEDS])
        logp = np.asarray(frag[Columns.ACTION_LOGP]) \
            if Columns.ACTION_LOGP in frag else None
        # TRUE per-step successors (env runners emit them with the
        # pre-reset final obs at done steps). Without the column, done
        # steps would otherwise self-loop (obs == next_obs), corrupting
        # V(next_obs) bootstrap for offline consumers — the in-fragment
        # successor obs[t+1] is the NEXT episode's reset obs there.
        next_obs = np.asarray(frag[Columns.NEXT_OBS]) \
            if Columns.NEXT_OBS in frag else None
        T, B = rewards.shape[:2]
        written = 0
        with self._lock:
            for b in range(B):
                lane = (source, b)
                eps = self._lane_eps.get(lane)
                if eps is None:
                    eps = self._next_eps()
                    self._lane_eps[lane] = eps
                carry = self._lane_carry.pop(lane, None)
                if carry is not None:
                    carry["next_obs"] = obs[0, b].tolist()
                    self._rows.append(carry)
                    written += 1
                for t in range(T):
                    done = bool(terms[t, b]) or bool(truncs[t, b])
                    if next_obs is not None:
                        successor = next_obs[t, b].tolist()
                    elif done:
                        # No successor column: keep the legacy
                        # self-loop ONLY as a last resort (documented:
                        # bootstrap at done steps then uses the
                        # pre-step obs; terminated steps mask V(s')
                        # anyway, truncated ones lose accuracy).
                        successor = obs[t, b].tolist()
                    elif t + 1 < T:
                        successor = obs[t + 1, b].tolist()
                    else:
                        successor = None
                    row: dict[str, Any] = {
                        "obs": obs[t, b].tolist(),
                        "next_obs": successor,
                        "actions": np.asarray(actions[t, b]).tolist(),
                        "rewards": float(rewards[t, b]),
                        "terminateds": bool(terms[t, b]),
                        "truncateds": bool(truncs[t, b]),
                        "eps_id": eps,
                    }
                    if logp is not None:
                        row["action_logp"] = float(logp[t, b])
                    if done:
                        eps = self._next_eps()
                        self._lane_eps[lane] = eps
                    if row["next_obs"] is None:
                        # Lane's last step, episode still live: hold it
                        # for the next fragment's obs[0].
                        self._lane_carry[lane] = row
                    else:
                        self._rows.append(row)
                        written += 1
            if len(self._rows) >= self.rows_per_file:
                self._flush_locked()
        return written

    def _next_eps(self) -> int:
        self._eps_counter += 1
        return self.worker_index * 1_000_000_000 + self._eps_counter

    # ------------------------------------------------------------- output

    def _shard_path(self, ext: str) -> str:
        path = os.path.join(
            self.path,
            f"output-{self.worker_index:03d}-{self._file_index:05d}.{ext}")
        self._file_index += 1
        return path

    def _flush_locked(self) -> None:
        if not self._rows:
            return
        rows, self._rows = self._rows, []
        if self.format == "json":
            with open(self._shard_path("json"), "w") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
            return
        import pyarrow as pa
        import pyarrow.parquet as pq

        table = pa.Table.from_pylist(rows)
        pq.write_table(table, self._shard_path("parquet"))

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            # Carried lane tails have no successor anymore: emit them
            # as truncated (the log ends mid-episode — same semantics
            # as a time-limit cut).
            for row in self._lane_carry.values():
                row["next_obs"] = row["obs"]
                row["truncateds"] = True
                self._rows.append(row)
            self._lane_carry.clear()
            self._flush_locked()


def read_offline_dataset(path: str):
    """Logged experience dir/file -> ray_tpu.data Dataset (reference:
    dataset_reader.py's input_=<path> resolution: format from the file
    extensions)."""
    import glob

    import ray_tpu.data as rd

    if os.path.isdir(path):
        parquet = sorted(glob.glob(os.path.join(path, "*.parquet")))
        jsons = sorted(glob.glob(os.path.join(path, "*.json")))
        if parquet and jsons:
            raise ValueError(
                f"{path} mixes parquet and json shards; pass one format")
        if parquet:
            return rd.read_parquet(parquet)
        if jsons:
            return rd.read_json(jsons)
        raise FileNotFoundError(f"no offline shards under {path}")
    if path.endswith(".parquet"):
        return rd.read_parquet([path])
    if path.endswith(".json"):
        return rd.read_json([path])
    raise ValueError(f"unsupported offline input: {path}")
