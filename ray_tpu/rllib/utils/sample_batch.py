"""Columnar sample batches.

Reference: rllib/policy/sample_batch.py:99 (SampleBatch) — a dict of
equal-length columns with concat/slice/minibatch utilities. Here columns
are numpy or jax arrays; batches are the unit shipped from env runners
to learners through the object store.
"""

from __future__ import annotations

from typing import Iterator, Mapping

import numpy as np


class Columns:
    """Canonical column names (reference: rllib/core/columns.py)."""

    OBS = "obs"
    NEXT_OBS = "next_obs"
    ACTIONS = "actions"
    REWARDS = "rewards"
    TERMINATEDS = "terminateds"
    TRUNCATEDS = "truncateds"
    ACTION_LOGP = "action_logp"
    ACTION_LOGITS = "action_logits"
    VF_PREDS = "vf_preds"
    ADVANTAGES = "advantages"
    VALUE_TARGETS = "value_targets"
    EPS_ID = "eps_id"
    T = "t"


class SampleBatch(dict):
    """A dict of columns, all with the same leading dimension.

    Reference: rllib/policy/sample_batch.py:99. Unlike the reference this
    is a plain dict subclass holding numpy/jax arrays; no compression or
    lazy views — the object store handles zero-copy.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)

    def __len__(self) -> int:
        for v in self.values():
            return int(np.shape(v)[0])
        return 0

    @property
    def count(self) -> int:
        return len(self)

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: np.random.Generator | None = None) -> "SampleBatch":
        rng = rng or np.random.default_rng()
        perm = rng.permutation(len(self))
        return SampleBatch({k: np.asarray(v)[perm] for k, v in self.items()})

    def minibatches(self, size: int,
                    rng: np.random.Generator | None = None,
                    shuffle: bool = True) -> Iterator["SampleBatch"]:
        """Equal-size minibatches; the tail remainder is dropped so every
        jitted update sees a static shape (XLA recompiles per shape)."""
        batch = self.shuffle(rng) if shuffle else self
        n = len(batch)
        for start in range(0, n - size + 1, size):
            yield batch.slice(start, start + size)

    @staticmethod
    def concat(batches: "list[SampleBatch]") -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([np.asarray(b[k]) for b in batches], axis=0)
            for k in keys
        })

    def to_numpy(self) -> "SampleBatch":
        return SampleBatch({k: np.asarray(v) for k, v in self.items()})

    def split_n(self, n: int) -> "list[SampleBatch]":
        """Split into n near-equal shards (for data-parallel learners)."""
        size = len(self) // n
        return [self.slice(i * size, (i + 1) * size) for i in range(n)]


def pad_to_multiple(batch: SampleBatch, multiple: int,
                    pad_value: float = 0.0) -> tuple[SampleBatch, np.ndarray]:
    """Pad all columns to a multiple of ``multiple`` along axis 0.

    Returns (padded_batch, mask) where mask is 1.0 for real rows. Keeps
    shapes static-friendly for XLA: a handful of bucket sizes instead of
    arbitrary lengths.
    """
    n = len(batch)
    target = ((n + multiple - 1) // multiple) * multiple
    pad = target - n
    mask = np.ones(target, dtype=np.float32)
    if pad:
        mask[n:] = 0.0
        batch = SampleBatch({
            k: np.concatenate(
                [np.asarray(v),
                 np.full((pad,) + np.shape(v)[1:], pad_value,
                         dtype=np.asarray(v).dtype)], axis=0)
            for k, v in batch.items()
        })
    return batch, mask


def fragment_to_transitions(frag: "SampleBatch") -> "SampleBatch":
    """Flatten a time-major [T, B] rollout fragment into (s, a, r, s',
    done) transition rows for replay buffers, dropping rows whose
    next_obs crosses a truncation boundary (the auto-reset obs belongs
    to a NEW episode). Shared by the off-policy algorithms (SAC/TD3;
    reference: the replay-ingest path of their torch learners)."""
    obs = np.asarray(frag[Columns.OBS])          # [T, B, obs]
    actions = np.asarray(frag[Columns.ACTIONS])  # [T, B, act]
    next_obs = obs[1:]
    keep = ~np.asarray(frag[Columns.TRUNCATEDS])[:-1].reshape(-1)
    return SampleBatch({
        Columns.OBS: obs[:-1].reshape((-1,) + obs.shape[2:])[keep],
        Columns.NEXT_OBS: next_obs.reshape((-1,) + obs.shape[2:])[keep],
        Columns.ACTIONS: actions[:-1].reshape(
            (-1,) + actions.shape[2:])[keep],
        Columns.REWARDS: np.asarray(
            frag[Columns.REWARDS])[:-1].reshape(-1)[keep],
        Columns.TERMINATEDS: np.asarray(
            frag[Columns.TERMINATEDS])[:-1].reshape(-1)[keep],
    })
