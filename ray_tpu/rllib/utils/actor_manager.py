"""Fault-tolerant actor fan-out.

Reference: rllib/utils/actor_manager.py (FaultTolerantActorManager) —
tolerates env-runner actor failures: broken actors are marked unhealthy,
calls skip them, and ``probe_unhealthy_actors`` restarts replacements.
"""

from __future__ import annotations

from typing import Any, Callable

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, ActorError, TaskError


class FaultTolerantActorManager:
    """Manages a homogeneous set of actor handles with health tracking."""

    def __init__(self, actors: list, *, actor_factory: Callable | None = None,
                 max_remote_requests_in_flight_per_actor: int = 2):
        self._actors: dict[int, Any] = dict(enumerate(actors))
        self._healthy: dict[int, bool] = {i: True for i in self._actors}
        self._factory = actor_factory
        self._max_in_flight = max_remote_requests_in_flight_per_actor
        self._in_flight: dict[int, list] = {i: [] for i in self._actors}

    # -- introspection ----------------------------------------------
    def num_actors(self) -> int:
        return len(self._actors)

    def num_healthy_actors(self) -> int:
        return sum(self._healthy.values())

    def healthy_actor_ids(self) -> list[int]:
        return [i for i, ok in self._healthy.items() if ok]

    def actor(self, actor_id: int):
        return self._actors[actor_id]

    # -- sync fan-out -------------------------------------------------
    def foreach_actor(self, fn_name: str, *args,
                      timeout: float | None = 60.0,
                      **kwargs) -> list:
        """Call ``fn_name(*args)`` on every healthy actor; returns results
        in actor-id order, skipping (and marking) failed actors."""
        return [result for _, result in self.foreach_actor_with_ids(
            fn_name, *args, timeout=timeout, **kwargs)]

    def foreach_actor_with_ids(self, fn_name: str, *args,
                               timeout: float | None = 60.0,
                               **kwargs) -> list:
        """Like foreach_actor but yields ``(actor_id, result)`` pairs —
        for consumers that key per-actor state (e.g. the offline
        writer's episode lanes), where a positional index would SHIFT
        when an actor fails and silently mix actors' streams."""
        refs = {}
        for i in self.healthy_actor_ids():
            method = getattr(self._actors[i], fn_name)
            refs[i] = method.remote(*args, **kwargs)
        results = []
        for i, ref in refs.items():
            try:
                results.append((i, ray_tpu.get(ref, timeout=timeout)))
            except (ActorError, ActorDiedError, TaskError, TimeoutError):
                self._healthy[i] = False
        return results

    def broadcast_async(self, fn_name: str, *args,
                        pending: dict | None = None, **kwargs) -> dict:
        """Backpressured async fan-out (weight broadcasts must not stall
        the learner; reference: IMPALA pushes weights asynchronously).

        At most ONE in-flight push per actor: an actor whose previous
        push hasn't resolved is skipped this round (its pending ref is
        carried over), so a slow runner never accumulates queued pushes
        each pinning a weights object. Resolved pushes are consumed so
        failures mark the actor unhealthy. Returns {actor_id: ref}."""
        pending = dict(pending or {})
        out: dict[int, Any] = {}
        for i in self.healthy_actor_ids():
            prev = pending.get(i)
            if prev is not None:
                ready, _ = ray_tpu.wait([prev], num_returns=1, timeout=0)
                if not ready:
                    out[i] = prev  # still in flight; skip this round
                    continue
                try:
                    ray_tpu.get(prev)
                except (ActorError, ActorDiedError, TaskError):
                    self._healthy[i] = False
                    continue
            method = getattr(self._actors[i], fn_name)
            out[i] = method.remote(*args, **kwargs)
        return out

    # -- async fan-out ------------------------------------------------
    def submit(self, fn_name: str, *args, actor_id: int | None = None,
               **kwargs):
        """Fire a call without waiting; bounded in-flight per actor.
        Returns (actor_id, ref) or None if saturated/unhealthy."""
        candidates = ([actor_id] if actor_id is not None
                      else self.healthy_actor_ids())
        for i in candidates:
            if not self._healthy.get(i):
                continue
            pending = self._in_flight[i]
            if pending:
                _, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=0)
            self._in_flight[i] = pending
            if len(self._in_flight[i]) >= self._max_in_flight:
                continue
            ref = getattr(self._actors[i], fn_name).remote(*args, **kwargs)
            self._in_flight[i].append(ref)
            return i, ref
        return None

    def pump(self, fn_name: str, pending: list, on_ready,
             timeout: float = 0.05) -> list:
        """One round of the async sampling pump shared by the
        throughput algorithms (IMPALA, APEX): saturate every healthy
        actor with ``fn_name`` requests up to the in-flight bound, then
        deliver whatever completed to ``on_ready(result)``. Returns the
        new pending list."""
        while True:
            sub = self.submit(fn_name)
            if sub is None:
                break
            pending.append(sub)
        ready, pending = self.fetch_ready(pending, timeout=timeout)
        for _, result in ready:
            on_ready(result)
        return pending

    def fetch_ready(self, refs: list, timeout: float = 0.01) -> tuple:
        """(ready_results, remaining_refs); failures mark actors sick."""
        if not refs:
            return [], []
        ready, _ = ray_tpu.wait(
            [r for _, r in refs], num_returns=len(refs), timeout=timeout)
        ready_set = {id(r) for r in ready}
        results, remaining = [], []
        for actor_id, ref in refs:
            if id(ref) in ready_set:
                try:
                    results.append((actor_id, ray_tpu.get(ref)))
                except (ActorError, ActorDiedError, TaskError):
                    self._healthy[actor_id] = False
            else:
                remaining.append((actor_id, ref))
        return results, remaining

    # -- recovery -----------------------------------------------------
    def probe_unhealthy_actors(self) -> list[int]:
        """Try to replace dead actors via the factory (reference:
        FaultTolerantActorManager.probe_unhealthy_actors)."""
        restored = []
        for i, ok in list(self._healthy.items()):
            if ok:
                continue
            try:
                ray_tpu.get(self._actors[i].ping.remote(), timeout=1.0)
                self._healthy[i] = True
                restored.append(i)
                continue
            except Exception:
                pass  # probe failed: falls through to respawn
            if self._factory is not None:
                self._actors[i] = self._factory(i)
                self._in_flight[i] = []
                self._healthy[i] = True
                restored.append(i)
        return restored
