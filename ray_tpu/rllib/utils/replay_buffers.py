"""Replay buffers for off-policy algorithms.

Reference: rllib/utils/replay_buffers/ (EpisodeReplayBuffer
episode_replay_buffer.py:14, prioritized variant). Stored as
preallocated numpy ring buffers over flat transitions — sampling
produces fixed-shape batches, so the learner's jitted update never
recompiles.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch


class ReplayBuffer:
    """Uniform FIFO transition buffer."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._storage: dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        """Append flat [N, ...] transitions."""
        n = len(batch)
        if not self._storage:
            for k, v in batch.items():
                v = np.asarray(v)
                self._storage[k] = np.zeros(
                    (self.capacity,) + v.shape[1:], dtype=v.dtype)
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._idx + np.arange(n)) % self.capacity
            self._storage[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._storage.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (Schaul et al. 2016).

    Reference: rllib/utils/replay_buffers/prioritized_episode_buffer.
    Priorities kept in a flat array; sampling is O(N) numpy (fine for
    host-side buffers — the TPU never sees this path).
    """

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, dtype=np.float64)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        idx = (self._idx + np.arange(n)) % self.capacity
        super().add(batch)
        self._priorities[idx] = self._max_priority

    def sample(self, batch_size: int) -> SampleBatch:
        prios = self._priorities[:self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights /= weights.max()
        out = SampleBatch({k: v[idx] for k, v in self._storage.items()})
        out["batch_indexes"] = idx
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prios = np.abs(td_errors) + 1e-6
        self._priorities[idx] = prios
        self._max_priority = max(self._max_priority, float(prios.max()))


class PrioritizedSequenceReplayBuffer:
    """Fixed-length SEQUENCE storage for recurrent Q-learning.

    Reference: R2D2's replay (Kapturowski et al. 2019) — units are
    whole [T] sequences, each carrying the recurrent state observed at
    its first step; priorities are per sequence (the eta-mix of max and
    mean TD magnitude is computed learner-side and pushed back via
    ``update_priorities``). Storage is a preallocated ring per column,
    so sampled batches are fixed-shape time-major [T, b] and the jitted
    learner update never recompiles.
    """

    SEQ_COLUMNS = (Columns.OBS, Columns.ACTIONS, Columns.REWARDS,
                   Columns.TERMINATEDS, Columns.TRUNCATEDS)

    def __init__(self, capacity_sequences: int = 4096,
                 alpha: float = 0.6, beta: float = 0.4, seed: int = 0):
        self.capacity = capacity_sequences
        self.alpha = alpha
        self.beta = beta
        self._storage: dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self._priorities = np.zeros(capacity_sequences, dtype=np.float64)
        self._max_priority = 1.0

    def __len__(self) -> int:
        return self._size

    def add_fragment(self, fragment: SampleBatch) -> int:
        """Split a [T, B] rollout fragment (with its "state_in" [B, H])
        into B sequences and append them. Returns sequences added."""
        state_in = np.asarray(fragment["state_in"])
        T, B = np.asarray(fragment[Columns.REWARDS]).shape
        if not self._storage:
            for k in self.SEQ_COLUMNS:
                v = np.asarray(fragment[k])
                self._storage[k] = np.zeros(
                    (self.capacity, T) + v.shape[2:], dtype=v.dtype)
            self._storage["state_in"] = np.zeros(
                (self.capacity,) + state_in.shape[1:],
                dtype=state_in.dtype)
        stored_T = self._storage[Columns.REWARDS].shape[1]
        if T != stored_T:
            raise ValueError(
                f"sequence length changed: buffer holds T={stored_T}, "
                f"fragment has T={T} (fixed shapes keep the jitted "
                f"update from recompiling)")
        idx = (self._idx + np.arange(B)) % self.capacity
        for k in self.SEQ_COLUMNS:
            # [T, B, ...] -> [B, T, ...] rows.
            self._storage[k][idx] = np.moveaxis(
                np.asarray(fragment[k]), 0, 1)
        self._storage["state_in"][idx] = state_in
        self._priorities[idx] = self._max_priority
        self._idx = (self._idx + B) % self.capacity
        self._size = min(self._size + B, self.capacity)
        return B

    def sample(self, num_sequences: int) -> SampleBatch:
        """Time-major [T, b] batch of ``num_sequences`` sequences with
        IS weights and indexes for the priority write-back."""
        prios = self._priorities[:self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, size=num_sequences, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights /= weights.max()
        out = SampleBatch()
        for k in self.SEQ_COLUMNS:
            out[k] = np.moveaxis(self._storage[k][idx], 0, 1)
        out["state_in"] = self._storage["state_in"][idx]
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx: np.ndarray,
                          seq_priorities: np.ndarray) -> None:
        prios = np.abs(np.asarray(seq_priorities)) + 1e-6
        self._priorities[np.asarray(idx)] = prios
        self._max_priority = max(self._max_priority, float(prios.max()))
