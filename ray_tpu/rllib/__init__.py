"""ray_tpu.rllib — reinforcement learning on the TPU-native runtime.

Reference: rllib/ (new API stack only — RLModule / Learner /
LearnerGroup / EnvRunner / Algorithm; see SURVEY.md §2.3). The compute
path is pure JAX: jitted policy steps on env runners, jitted
loss+update on learners (GAE and V-trace as `lax.scan`), GSPMD meshes
instead of DDP wrappers for multi-device learners.
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.core.learner import JaxLearner, Learner, compute_gae
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import (
    DefaultActorCriticModule,
    RLModule,
    RLModuleSpec,
)
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.vector_env import (
    CartPoleVectorEnv,
    VectorEnv,
    make_vector_env,
    register_env,
)
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "CartPoleVectorEnv",
    "Columns",
    "DQN",
    "DQNConfig",
    "DefaultActorCriticModule",
    "FaultTolerantActorManager",
    "IMPALA",
    "IMPALAConfig",
    "JaxLearner",
    "Learner",
    "LearnerGroup",
    "PPO",
    "PPOConfig",
    "PrioritizedReplayBuffer",
    "RLModule",
    "RLModuleSpec",
    "ReplayBuffer",
    "SampleBatch",
    "SingleAgentEnvRunner",
    "VectorEnv",
    "compute_gae",
    "make_vector_env",
    "register_env",
]
