"""ray_tpu.rllib — reinforcement learning on the TPU-native runtime.

Reference: rllib/ (new API stack only — RLModule / Learner /
LearnerGroup / EnvRunner / Algorithm; see SURVEY.md §2.3). The compute
path is pure JAX: jitted policy steps on env runners, jitted
loss+update on learners (GAE and V-trace as `lax.scan`), GSPMD meshes
instead of DDP wrappers for multi-device learners.
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.apex_dqn import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.ars import ARS, ARSConfig
from ray_tpu.rllib.algorithms.bandits import (
    BanditLinTS,
    BanditLinTSConfig,
    BanditLinUCB,
    BanditLinUCBConfig,
    LinearContextualBanditEnv,
    register_bandit_env,
)
from ray_tpu.rllib.algorithms.bc import (
    BC,
    BCConfig,
    MARWIL,
    MARWILConfig,
)
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.crr import CRR, CRRConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.core.catalog import Catalog, ConvActorCriticModule
from ray_tpu.rllib.algorithms.dt import DT, DTConfig, DTModule
from ray_tpu.rllib.algorithms.es import ES, ESConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.multi_agent_ppo import (
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.algorithms.pg import A2C, A2CConfig, PG, PGConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.qmix import (
    QMIX,
    QMIXConfig,
    TwoStepCooperativeGame,
)
from ray_tpu.rllib.algorithms.r2d2 import GRUQModule, R2D2, R2D2Config
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.simple_q import SimpleQ, SimpleQConfig
from ray_tpu.rllib.algorithms.td3 import DDPG, DDPGConfig, TD3, TD3Config
from ray_tpu.rllib.core.learner import JaxLearner, Learner, compute_gae
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import (
    DefaultActorCriticModule,
    RLModule,
    RLModuleSpec,
)
from ray_tpu.rllib.core.multi_rl_module import (
    MultiRLModule,
    MultiRLModuleSpec,
)
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.multi_agent_env import (
    IndependentMultiAgentEnv,
    MultiAgentVectorEnv,
    make_multi_agent,
    register_multi_agent_env,
)
from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner
from ray_tpu.rllib.env.vector_env import (
    CartPoleVectorEnv,
    PendulumVectorEnv,
    VectorEnv,
    make_vector_env,
    register_env,
)
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedReplayBuffer,
    PrioritizedSequenceReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.utils.sample_batch import Columns, SampleBatch

__all__ = [
    "APPO",
    "APPOConfig",
    "BanditLinTS",
    "BanditLinTSConfig",
    "BanditLinUCB",
    "BanditLinUCBConfig",
    "LinearContextualBanditEnv",
    "register_bandit_env",
    "BC",
    "BCConfig",
    "MARWIL",
    "MARWILConfig",
    "Algorithm",
    "AlgorithmConfig",
    "CartPoleVectorEnv",
    "Columns",
    "Catalog",
    "ConvActorCriticModule",
    "DQN",
    "DQNConfig",
    "DreamerV3",
    "DreamerV3Config",
    "DT",
    "DTConfig",
    "DTModule",
    "DefaultActorCriticModule",
    "FaultTolerantActorManager",
    "CQL",
    "CQLConfig",
    "ES",
    "ESConfig",
    "IMPALA",
    "IMPALAConfig",
    "IndependentMultiAgentEnv",
    "JaxLearner",
    "Learner",
    "LearnerGroup",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "MultiAgentVectorEnv",
    "MultiRLModule",
    "MultiRLModuleSpec",
    "A2C",
    "A2CConfig",
    "ARS",
    "ARSConfig",
    "ApexDQN",
    "ApexDQNConfig",
    "CRR",
    "CRRConfig",
    "PG",
    "PGConfig",
    "PPO",
    "PPOConfig",
    "PendulumVectorEnv",
    "SimpleQ",
    "SimpleQConfig",
    "PrioritizedReplayBuffer",
    "PrioritizedSequenceReplayBuffer",
    "GRUQModule",
    "QMIX",
    "QMIXConfig",
    "TwoStepCooperativeGame",
    "R2D2",
    "R2D2Config",
    "RLModule",
    "RLModuleSpec",
    "ReplayBuffer",
    "SAC",
    "SACConfig",
    "DDPG",
    "DDPGConfig",
    "TD3",
    "TD3Config",
    "SampleBatch",
    "SingleAgentEnvRunner",
    "VectorEnv",
    "compute_gae",
    "make_multi_agent",
    "make_vector_env",
    "register_env",
    "register_multi_agent_env",
]
