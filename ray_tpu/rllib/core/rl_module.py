"""RLModule — the neural-network abstraction of the new API stack.

Reference: rllib/core/rl_module/rl_module.py:237 with the three forward
passes at :601/:624/:649 (forward_inference / forward_exploration /
forward_train).

TPU-first departure: the reference RLModule is a stateful
torch.nn.Module; here an RLModule is a *functional* spec — parameters
are an explicit pytree created by ``init`` and threaded through pure
``forward_*`` functions. That makes every pass jittable/shardable with
no wrapper (the reference needs TorchDDPRLModule,
core/learner/torch/torch_learner.py:265, to data-parallelize; under
GSPMD the same function runs on any mesh unchanged).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


class RLModule:
    """Functional policy/value network bundle.

    Subclasses implement ``init`` and the ``forward_*`` methods as pure
    functions of (params, batch, rng). ``output`` dicts use the column
    names in utils/sample_batch.py (ACTION_LOGITS, VF_PREDS, ...).
    """

    def init(self, rng: jax.Array) -> Any:
        raise NotImplementedError

    def forward_inference(self, params, batch: dict, rng=None) -> dict:
        """Greedy/deterministic pass (reference rl_module.py:601)."""
        raise NotImplementedError

    def forward_exploration(self, params, batch: dict, rng=None) -> dict:
        """Sampling pass used by env runners (reference :624)."""
        raise NotImplementedError

    def forward_train(self, params, batch: dict, rng=None) -> dict:
        """Train-time pass used inside the learner loss (reference :649)."""
        raise NotImplementedError


@dataclass
class RLModuleSpec:
    """Serializable module constructor (reference:
    rl_module.py RLModuleSpec). Shipped to env-runner and learner actors;
    ``build()`` runs on the receiving side."""

    module_class: type | None = None
    observation_size: int = 0
    num_actions: int = 0      # discrete action count (0 if continuous)
    action_size: int = 0      # continuous action dim (0 if discrete)
    model_config: dict = field(default_factory=dict)

    def build(self) -> "RLModule":
        cls = self.module_class
        if cls is None:
            # Catalog selection: MLP towers for flat obs, CNN encoder
            # for image obs / an explicit model_config["encoder"]
            # (reference: the catalog picks the default model).
            from ray_tpu.rllib.core.catalog import Catalog

            cls = Catalog.resolve(self)
        kwargs = dict(self.model_config)
        if self.action_size:
            kwargs.setdefault("action_size", self.action_size)
        return cls(self.observation_size, self.num_actions, **kwargs)


def _mlp_init(rng, sizes):
    params = []
    for i, (n_in, n_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, key = jax.random.split(rng)
        scale = jnp.sqrt(2.0 / n_in)
        params.append({
            "w": jax.random.normal(key, (n_in, n_out)) * scale,
            "b": jnp.zeros(n_out),
        })
    return params


def _mlp_apply(params, x, final_activation=None):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
        elif final_activation is not None:
            x = final_activation(x)
    return x


class DefaultActorCriticModule(RLModule):
    """Shared-nothing MLP actor-critic for discrete actions.

    Reference analogue: rllib's default PPO catalog model (separate pi and
    vf MLP towers, tanh activations).
    """

    def __init__(self, observation_size: int, num_actions: int,
                 hidden: tuple = (64, 64), **_):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, rng):
        pi_rng, vf_rng = jax.random.split(rng)
        sizes = (self.observation_size,) + self.hidden
        return {
            "pi": _mlp_init(pi_rng, sizes + (self.num_actions,)),
            "vf": _mlp_init(vf_rng, sizes + (1,)),
        }

    def _logits_and_value(self, params, obs):
        logits = _mlp_apply(params["pi"], obs)
        value = _mlp_apply(params["vf"], obs)[..., 0]
        return logits, value

    def forward_inference(self, params, batch, rng=None):
        logits, value = self._logits_and_value(params, batch["obs"])
        return {"action_logits": logits, "vf_preds": value,
                "actions": jnp.argmax(logits, axis=-1)}

    def forward_exploration(self, params, batch, rng=None):
        logits, value = self._logits_and_value(params, batch["obs"])
        actions = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)
        action_logp = jnp.take_along_axis(
            logp, actions[..., None], axis=-1)[..., 0]
        return {"action_logits": logits, "vf_preds": value,
                "actions": actions, "action_logp": action_logp}

    def forward_train(self, params, batch, rng=None):
        logits, value = self._logits_and_value(params, batch["obs"])
        return {"action_logits": logits, "vf_preds": value}


def categorical_logp(logits: jax.Array, actions: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(logp, actions[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]


def categorical_entropy(logits: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def categorical_kl(logits_p: jax.Array, logits_q: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits_p)
    logq = jax.nn.log_softmax(logits_q)
    return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)
