"""MultiRLModule — a dict of RLModules keyed by policy (module) id.

Reference: rllib/core/rl_module/multi_rl_module.py (MultiRLModule holds
ModuleID -> RLModule; MultiRLModuleSpec builds it). Parameters here are
a dict-of-pytrees {policy_id: params}, so the whole multi-policy state
remains one pytree — checkpointable/shippable like any other.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax

from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec


@dataclass
class MultiRLModuleSpec:
    """{policy_id: RLModuleSpec}; build() -> {policy_id: RLModule}."""

    module_specs: dict = field(default_factory=dict)

    def build(self) -> "MultiRLModule":
        return MultiRLModule(
            {pid: spec.build() for pid, spec in self.module_specs.items()})


class MultiRLModule:
    def __init__(self, modules: dict):
        self._modules = modules

    def __getitem__(self, policy_id: str) -> RLModule:
        return self._modules[policy_id]

    def __contains__(self, policy_id: str) -> bool:
        return policy_id in self._modules

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def init(self, rng: jax.Array) -> dict:
        keys = jax.random.split(rng, len(self._modules))
        return {pid: mod.init(k)
                for (pid, mod), k in zip(self._modules.items(), keys)}
