"""LearnerGroup — scale-out container for learners.

Reference: rllib/core/learner/learner_group.py:71 (update_from_batch
:210, async updates with an in-flight cap :180-188).

Two scale-out modes, both TPU-idiomatic:

1. ``num_learners == 0`` (default): ONE local learner. With
   ``config.num_devices_per_learner > 1`` (or -1 = all local devices)
   its jitted update runs over a 1-D `jax.sharding.Mesh` — GSPMD shards
   the batch and inserts the gradient all-reduce over ICI. This replaces
   the reference's DDP-across-learner-actors for the single-host case
   (torch_learner.py:265).
2. ``num_learners > 0``: learner ACTORS (one per host in a real
   multi-host deployment). Each computes gradients on its batch shard;
   the group tree-averages and applies everywhere — parameter-server
   style fan-in over the object store (the DCN plane), while intra-host
   parallelism stays inside each learner's mesh.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class LearnerGroup:
    def __init__(self, *, learner_class: type,
                 module_spec: RLModuleSpec, config=None):
        self._num_learners = getattr(config, "num_learners", 0) or 0
        if self._num_learners == 0:
            mesh = self._build_local_mesh(
                getattr(config, "num_devices_per_learner", 1))
            self._local = learner_class(module_spec, config, mesh=mesh)
            self._actors = None
        else:
            self._local = None
            RemoteLearner = ray_tpu.remote(learner_class)
            self._actors = [
                RemoteLearner.remote(module_spec, config)
                for _ in range(self._num_learners)
            ]
            # All learners must start from identical params: broadcast
            # learner 0's state.
            state = ray_tpu.get(self._actors[0].get_state.remote())
            ref = ray_tpu.put(state)
            ray_tpu.get([a.set_state.remote(ref) for a in self._actors[1:]])

    @staticmethod
    def _build_local_mesh(num_devices: int):
        """1-D data mesh over local devices; -1 means all of them."""
        if num_devices in (0, 1):
            return None
        from jax.sharding import Mesh
        devices = jax.local_devices()
        n = len(devices) if num_devices == -1 else num_devices
        if n > len(devices):
            raise ValueError(
                f"num_devices_per_learner={n} but only "
                f"{len(devices)} local devices")
        return Mesh(np.array(devices[:n]), ("batch",))

    # -- update -------------------------------------------------------
    def update_from_batch(self, batch: SampleBatch,
                          shard: bool = True,
                          sync_metrics: bool = True) -> dict:
        """One gradient step over the full group (reference:
        learner_group.py:210).

        ``shard=False`` ships the whole batch to one learner round-robin
        (IMPALA's async pattern: time-major batches can't be row-split
        without breaking the V-trace scan)."""
        if self._local is not None:
            return self._local.update_from_batch(
                batch, sync_metrics=sync_metrics)
        if not shard:
            self._rr = getattr(self, "_rr", -1) + 1
            actor = self._actors[self._rr % self._num_learners]
            metrics = ray_tpu.get(actor.update_from_batch.remote(batch))
            # Weight drift between learners is bounded by re-syncing from
            # the updated learner.
            state_ref = actor.get_weights.remote()
            ray_tpu.get([a.set_weights.remote(state_ref)
                         for a in self._actors if a is not actor])
            return metrics
        shards = batch.split_n(self._num_learners)
        grad_refs = [a.compute_gradients.remote(s)
                     for a, s in zip(self._actors, shards)]
        results = ray_tpu.get(grad_refs)
        grads = [g for g, _ in results]
        metrics_list = [m for _, m in results]
        mean_grads = jax.tree_util.tree_map(
            lambda *gs: np.mean(np.stack(gs), axis=0), *grads)
        ref = ray_tpu.put(mean_grads)
        ray_tpu.get([a.apply_gradients.remote(ref) for a in self._actors])
        return {k: float(np.mean([m[k] for m in metrics_list]))
                for k in metrics_list[0]}

    # -- delegation ---------------------------------------------------
    def call(self, method: str, *args):
        """Invoke an arbitrary learner method on the first learner."""
        if self._local is not None:
            return getattr(self._local, method)(*args)
        return ray_tpu.get(getattr(self._actors[0], method).remote(*args))

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def set_weights(self, weights) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            ref = ray_tpu.put(weights)
            ray_tpu.get([a.set_weights.remote(ref) for a in self._actors])

    def get_state(self) -> dict:
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, state: dict) -> None:
        if self._local is not None:
            self._local.set_state(state)
        else:
            ref = ray_tpu.put(state)
            ray_tpu.get([a.set_state.remote(ref) for a in self._actors])

    def shutdown(self) -> None:
        if self._actors:
            for a in self._actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass  # learner already dead at teardown
