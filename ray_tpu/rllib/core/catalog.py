"""Model catalog: observation/action specs -> network architecture.

Reference: rllib/core/models/catalog.py — the Catalog inspects the
observation space and model_config and picks encoder + head components
(MLP for vectors, CNN for images, the framework-specific builders).
Here the same decision produces FUNCTIONAL jax modules: every component
is an (init, apply) pair over explicit param pytrees, so whatever the
catalog assembles is jittable and GSPMD-shardable unchanged.

Selection rules (Catalog.resolve):
- flat observations            -> DefaultActorCriticModule (MLP towers)
- rank-3 observations [H,W,C]  -> ConvActorCriticModule (CNN encoder +
  pi/vf heads); filters from model_config["conv_filters"] as a list of
  (out_channels, kernel, stride), defaulting to an Atari-style stack
- model_config["encoder"]      -> explicit override: "mlp" | "cnn"

Recurrent policies are separate module families, not encoder options:
R2D2's GRUQModule (rllib/algorithms/r2d2.py) and the Decision
Transformer (rllib/algorithms/dt.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.rllib.core.rl_module import (
    DefaultActorCriticModule,
    _mlp_apply,
    _mlp_init,
)

DEFAULT_CONV_FILTERS = ((16, 4, 2), (32, 4, 2), (64, 3, 2))


def build_cnn_encoder(obs_shape: tuple, conv_filters=None,
                      hidden_out: int = 256):
    """-> (init_fn(rng) -> params, apply_fn(params, x) -> [B, F], F).

    x is [B, H, W, C] float. Conv stack + flatten + one dense layer;
    NHWC layout with feature-last filters — the layout XLA prefers on
    TPU (channels on the minor-most, 128-lane dimension).
    """
    filters = tuple(conv_filters or DEFAULT_CONV_FILTERS)
    h, w, c = obs_shape

    def init(rng):
        params = {"conv": []}
        in_c = c
        hh, ww = h, w
        for out_c, k, s in filters:
            rng, key = jax.random.split(rng)
            scale = jnp.sqrt(2.0 / (k * k * in_c))
            params["conv"].append({
                "w": jax.random.normal(key, (k, k, in_c, out_c)) * scale,
                "b": jnp.zeros(out_c),
            })
            hh = max(1, -(-hh // s))
            ww = max(1, -(-ww // s))
            in_c = out_c
        flat = hh * ww * in_c
        rng, key = jax.random.split(rng)
        params["dense"] = _mlp_init(key, (flat, hidden_out))
        return params

    strides = tuple(s for _, _, s in filters)

    def apply(params, x):
        for layer, s in zip(params["conv"], strides):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + layer["b"])
        x = x.reshape(x.shape[:-3] + (-1,))
        return jnp.tanh(_mlp_apply(params["dense"], x))

    return init, apply, hidden_out


class ConvActorCriticModule(DefaultActorCriticModule):
    """CNN encoder shared by pi/vf heads, for image observations
    (reference: the catalog's CNN encoder + shared-encoder AC heads).

    Subclasses DefaultActorCriticModule: only param construction and
    the obs -> (logits, value) mapping differ; the three forward_*
    passes are inherited so the action/logp semantics cannot diverge.
    """

    def __init__(self, observation_size: int, num_actions: int,
                 obs_shape: tuple = (), conv_filters=None,
                 hidden: tuple = (256,), **_):
        if len(obs_shape) != 3:
            raise ValueError(
                f"ConvActorCriticModule needs [H, W, C] obs, got "
                f"{obs_shape}")
        super().__init__(observation_size, num_actions, hidden=hidden)
        self.obs_shape = tuple(obs_shape)
        self._enc_init, self._enc_apply, self._enc_out = \
            build_cnn_encoder(self.obs_shape, conv_filters,
                              hidden_out=int(hidden[0]))

    def init(self, rng):
        enc_rng, pi_rng, vf_rng = jax.random.split(rng, 3)
        return {
            "encoder": self._enc_init(enc_rng),
            "pi": _mlp_init(pi_rng, (self._enc_out, self.num_actions)),
            "vf": _mlp_init(vf_rng, (self._enc_out, 1)),
        }

    def _logits_and_value(self, params, obs):
        obs = jnp.asarray(obs, dtype=jnp.float32)
        if obs.ndim == len(self.obs_shape):  # unbatched guard
            obs = obs[None]
        feat = self._enc_apply(params["encoder"], obs)
        return (_mlp_apply(params["pi"], feat),
                _mlp_apply(params["vf"], feat)[..., 0])


class Catalog:
    """Pick a module class for a spec (reference: catalog.py's
    get_encoder_config + the default model pipeline)."""

    @staticmethod
    def resolve(spec) -> type:
        from ray_tpu.rllib.core.rl_module import DefaultActorCriticModule

        cfg = spec.model_config or {}
        encoder = cfg.get("encoder")
        obs_shape = tuple(cfg.get("obs_shape") or ())
        if encoder == "cnn" or (encoder is None and len(obs_shape) == 3):
            return ConvActorCriticModule
        if encoder not in (None, "mlp"):
            raise ValueError(
                f"unknown encoder {encoder!r} (catalog: mlp, cnn)")
        return DefaultActorCriticModule
