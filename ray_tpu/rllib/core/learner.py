"""JaxLearner — gradient updates on TPU.

Reference: rllib/core/learner/learner.py:106 (Learner; compute_loss
:871, _update :1247) and torch_learner.py:52. The reference
data-parallelizes by wrapping modules in DDP
(torch_learner.py:265,384-386); here the whole update is ONE jitted
pure function — running it under a `jax.sharding.Mesh` with batch-
sharded inputs makes XLA insert the gradient all-reduce over ICI
(GSPMD), so a "multi-learner" setup is just the same function on a
bigger mesh.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class Learner:
    """Base learner: owns module params, optimizer state, jitted update.

    Subclasses implement ``compute_loss(params, batch, rng) ->
    (loss, metrics_dict)`` as a pure function (reference: Learner.
    compute_loss learner.py:871).

    With a ``mesh``, batches are device_put batch-sharded over it and
    params replicated; GSPMD inserts the gradient all-reduce over ICI
    (the reference needs DDP for this, torch_learner.py:384-386).
    ``batch_axis`` names which input axis is the data axis (IMPALA's
    time-major [T, B] batches set it to 1).
    """

    batch_axis: int = 0

    def __init__(self, module_spec: RLModuleSpec, config=None,
                 mesh=None):
        self.config = config
        self.module: RLModule = module_spec.build()
        self._mesh = mesh
        self._rng = jax.random.PRNGKey(
            getattr(config, "seed", 0) if config is not None else 0)
        self._rng, init_rng = jax.random.split(self._rng)
        self.params = self.module.init(init_rng)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._replicated = NamedSharding(self._mesh, P())
            self.params = jax.device_put(self.params, self._replicated)
        self.optimizer = self.configure_optimizer()
        self.opt_state = self.optimizer.init(self.params)
        self._update_fn = None  # lazily jitted
        self._steps = 0

    # -- to override -------------------------------------------------
    def configure_optimizer(self) -> optax.GradientTransformation:
        lr = getattr(self.config, "lr", 3e-4) if self.config else 3e-4
        grad_clip = getattr(self.config, "grad_clip", None) \
            if self.config else None
        tx = optax.adam(lr)
        if grad_clip:
            tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
        return tx

    def compute_loss(self, params, batch: dict, rng) -> tuple:
        raise NotImplementedError

    # -- update path -------------------------------------------------
    def _build_update(self) -> Callable:
        def update(params, opt_state, batch, rng):
            (loss, metrics), grads = jax.value_and_grad(
                self.compute_loss, has_aux=True)(params, batch, rng)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, metrics

        # Under a mesh the batch arrives device_put with a batch-sharded
        # NamedSharding (see _device_batch); jit + GSPMD then derives the
        # gradient all-reduce automatically — no explicit in_shardings
        # needed, and the same compiled fn serves 1..N devices.
        # params/opt_state are donated: they are replaced by the return
        # values every step, so XLA may update buffers in place instead
        # of allocating + copying per update (the high-rate IMPALA path
        # calls this hundreds of times per second).
        return jax.jit(update, donate_argnums=(0, 1))

    def _device_batch(self, batch: SampleBatch) -> dict:
        if self._mesh is None:
            # ONE device_put for the whole pytree: per-column transfers
            # each pay a dispatch (and, on remote devices, a round
            # trip); a single call batches them.
            return jax.device_put(dict(batch))
        # tree_map so columns may themselves be pytrees (e.g. DQN ships
        # its target-net params inside the batch to keep the update pure).
        arrays = jax.tree_util.tree_map(jnp.asarray, dict(batch))
        from jax.sharding import NamedSharding, PartitionSpec as P
        n = self._mesh.size
        axis = self.batch_axis
        out = {}
        for k, v in arrays.items():
            if (isinstance(v, jax.Array) and v.ndim > axis
                    and v.shape[axis] % n == 0):
                spec = [None] * v.ndim
                spec[axis] = self._mesh.axis_names[0]
                out[k] = jax.device_put(
                    v, NamedSharding(self._mesh, P(*spec)))
            else:
                # Pytree columns (e.g. target params) and non-divisible
                # arrays (e.g. [B] bootstrap values in time-major batches)
                # are replicated.
                out[k] = jax.device_put(v, self._replicated)
        return out

    def update_from_batch(self, batch: SampleBatch,
                          sync_metrics: bool = True) -> dict:
        """One gradient step on one (already minibatched) batch.

        Reference: Learner._update (learner.py:1247).

        ``sync_metrics=False`` returns the metrics as device arrays
        WITHOUT blocking — high-rate loops (IMPALA) convert once per
        reporting interval instead of paying a device→host sync per
        update (per-scalar float() is one round trip each)."""
        if self._update_fn is None:
            self._update_fn = self._build_update()
        self._rng, step_rng = jax.random.split(self._rng)
        dev_batch = self._device_batch(batch)
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, dev_batch, step_rng)
        self._steps += 1
        if not sync_metrics:
            return metrics
        host = jax.device_get(metrics)  # one transfer for all scalars
        return {k: float(v) for k, v in host.items()}

    # -- gradient fan-in path (actor-based LearnerGroup) --------------
    def compute_gradients(self, batch: SampleBatch) -> tuple:
        """(grads, metrics) on this learner's shard — used when learners
        are separate actors/hosts and the group averages gradients
        (reference: DDP allreduce in torch_learner.py:384-386; here the
        reduction is done by the group, see learner_group.py)."""
        if not hasattr(self, "_grad_fn"):
            def grad_fn(params, batch, rng):
                (loss, metrics), grads = jax.value_and_grad(
                    self.compute_loss, has_aux=True)(params, batch, rng)
                metrics = dict(metrics)
                metrics["total_loss"] = loss
                return grads, metrics
            self._grad_fn = jax.jit(grad_fn)
        self._rng, step_rng = jax.random.split(self._rng)
        grads, metrics = self._grad_fn(
            self.params, self._device_batch(batch), step_rng)
        return (jax.device_get(grads),
                {k: float(v) for k, v in metrics.items()})

    def apply_gradients(self, grads) -> None:
        if not hasattr(self, "_apply_fn"):
            def apply_fn(params, opt_state, grads):
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state
            self._apply_fn = jax.jit(apply_fn)
        grads = jax.tree_util.tree_map(jnp.asarray, grads)
        self.params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, grads)
        self._steps += 1

    # -- state -------------------------------------------------------
    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)
        if self._mesh is not None:
            self.params = jax.device_put(self.params, self._replicated)

    def get_state(self) -> dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "steps": self._steps,
        }

    def set_state(self, state: dict) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self.opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["opt_state"])
        self._steps = state.get("steps", 0)


class TargetNetworkLearner(Learner):
    """Learner with a periodically-refreshed target network.

    The target params ride INSIDE each batch so the jitted update stays
    a pure function of its inputs (a closed-over pytree would be baked
    in as a compile-time constant and never update), and both the
    direct path (update_from_batch) and the sharded LearnerGroup path
    (compute_gradients/apply_gradients, which bypasses
    update_from_batch) inject + refresh identically. Shared by DQN,
    CRR, QMIX, and R2D2 (reference: each torch learner carries its own
    TargetNetworkAPI implementation)."""

    def __init__(self, module_spec, config=None, mesh=None):
        super().__init__(module_spec, config, mesh)
        self.target_params = jax.tree_util.tree_map(
            jnp.copy, self.params)

    def _maybe_refresh_target(self) -> None:
        if self._steps % getattr(self.config, "target_update_freq",
                                 100) == 0:
            self.target_params = jax.tree_util.tree_map(
                jnp.copy, self.params)

    def _with_target(self, batch: SampleBatch) -> SampleBatch:
        batch = SampleBatch(batch)
        batch["target_params"] = self.target_params
        return batch

    def update_from_batch(self, batch: SampleBatch,
                          sync_metrics: bool = True) -> dict:
        metrics = super().update_from_batch(
            self._with_target(batch), sync_metrics=sync_metrics)
        self._maybe_refresh_target()
        return metrics

    def compute_gradients(self, batch: SampleBatch) -> tuple:
        return super().compute_gradients(self._with_target(batch))

    def apply_gradients(self, grads) -> None:
        super().apply_gradients(grads)
        self._maybe_refresh_target()


JaxLearner = Learner  # the only framework here is JAX


def compute_gae(rewards: jax.Array, values: jax.Array,
                bootstrap_value: jax.Array, terminateds: jax.Array,
                truncateds: jax.Array, gamma: float,
                lam: float) -> tuple:
    """Generalized advantage estimation over a [T, B] rollout.

    Reference behavior: rllib/evaluation/postprocessing (GAE); computed
    here as a reverse `lax.scan` inside jit — the whole advantage pass
    stays on device, no per-episode host loop.

    truncated steps bootstrap from the value function; terminated steps
    cut the return to the immediate reward.
    """
    not_term = 1.0 - terminateds.astype(jnp.float32)
    # Value of the state after step t: v_{t+1}, bootstrapped at the end.
    next_values = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    # At a boundary (terminated OR truncated) the next row of `values`
    # belongs to a different episode; for truncation we have no stored
    # v(s_{t+1}) for the pre-reset state, so we approximate it with the
    # stored value (standard rollout-fragment practice).
    boundary = jnp.logical_or(terminateds, truncateds).astype(jnp.float32)
    next_values = jnp.where(truncateds, values, next_values)

    deltas = rewards + gamma * not_term * next_values - values

    def scan_fn(carry, xs):
        delta, cont = xs
        adv = delta + gamma * lam * cont * carry
        return adv, adv

    # GAE accumulation stops at any episode boundary.
    cont = 1.0 - boundary
    _, advantages = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap_value),
        (deltas, cont), reverse=True)
    value_targets = advantages + values
    return advantages, value_targets
