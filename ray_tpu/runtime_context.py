"""Runtime context — introspection from inside tasks/actors.

Reference: python/ray/runtime_context.py (get_runtime_context()).
"""

from __future__ import annotations

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.worker import RuntimeContext as _Ctx


class RuntimeContextAPI:
    @property
    def job_id(self):
        runtime = worker_mod.auto_init()
        return _Ctx.current().get("job_id", runtime.job_id)

    def get_job_id(self) -> str:
        return self.job_id.hex()

    @property
    def task_id(self):
        return _Ctx.current().get("task_id")

    def get_task_id(self) -> str | None:
        task_id = self.task_id
        return task_id.hex() if task_id is not None else None

    @property
    def actor_id(self):
        return _Ctx.current().get("actor_id")

    def get_actor_id(self) -> str | None:
        actor_id = self.actor_id
        return actor_id.hex() if actor_id is not None else None

    @property
    def node_id(self):
        runtime = worker_mod.auto_init()
        return _Ctx.current().get("node_id", runtime.head_node_id)

    def get_node_id(self) -> str:
        return self.node_id.hex()

    @property
    def namespace(self) -> str:
        return worker_mod.auto_init().namespace

    def get_assigned_resources(self) -> dict:
        return _Ctx.current().get("resources", {})

    def get_task_deadline(self) -> float | None:
        """The in-flight call's ABSOLUTE end-to-end deadline
        (time.time() clock) inherited from the PR-7 overload-control
        plane (``.options(_deadline_s=...)`` / serve
        ``request_timeout_s``), or None when no budget is armed.
        Long-lived engines (e.g. the LLM engine) read this so their
        internal queues refuse dead work typed instead of serving
        results nobody is waiting for."""
        from ray_tpu._private import request_context

        return request_context.current_deadline()


def get_runtime_context() -> RuntimeContextAPI:
    return RuntimeContextAPI()
