"""Prometheus metrics agent.

Reference: python/ray/_private/metrics_agent.py + prometheus_exporter.py
(OpenCensus → Prometheus bridge per node). Here: the process-wide metric
registry (ray_tpu.util.metrics.REGISTRY) plus built-in runtime
collectors, served in Prometheus text exposition format over HTTP at
``/metrics``.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ray_tpu.util.metrics import REGISTRY, _escape_label


def install_runtime_collectors(runtime):
    """Register scrape-time collectors over the runtime's live tables
    (tasks by state, actors by state, store bytes, nodes alive) —
    the metric set mirrors stats/metric_defs.cc core metrics.

    Returns the deregistration callable (MetricsAgent.shutdown uses it
    so a re-init cannot scrape a dead runtime's tables)."""

    def collect() -> list[str]:
        lines = []
        by_state: dict[str, int] = {}
        for ev in runtime.gcs.list_task_events():
            by_state[ev.state] = by_state.get(ev.state, 0) + 1
        lines.append("# TYPE ray_tpu_tasks gauge")
        for state, n in sorted(by_state.items()):
            lines.append(f'ray_tpu_tasks{{state="{state}"}} {n}')

        actor_states: dict[str, int] = {}
        for rec in runtime.gcs.list_actors():
            actor_states[rec.state] = actor_states.get(rec.state, 0) + 1
        lines.append("# TYPE ray_tpu_actors gauge")
        for state, n in sorted(actor_states.items()):
            lines.append(f'ray_tpu_actors{{state="{state}"}} {n}')

        stats = runtime.store.stats()
        lines.append("# TYPE ray_tpu_object_store_memory_bytes gauge")
        lines.append(
            f"ray_tpu_object_store_memory_bytes {stats['memory_used_bytes']}")
        lines.append("# TYPE ray_tpu_object_store_num_objects gauge")
        lines.append(
            f"ray_tpu_object_store_num_objects {stats['num_objects']}")
        lines.append("# TYPE ray_tpu_spilled_bytes_total counter")
        lines.append(
            f"ray_tpu_spilled_bytes_total {stats['spilled_bytes_total']}")

        # Spill tier (spill_manager.py): driver-side counters for the
        # value + export stores as one labeled family (daemon counters
        # ride the per-node series below as the "spill" group).
        try:
            spill = runtime.spill_stats()
        except Exception:  # noqa: BLE001 — partial runtime teardown
            spill = {}
        lines.append("# TYPE ray_tpu_spill_total counter")
        for key, value in sorted(spill.items()):
            if isinstance(value, (int, float)) and key != "restore_p50_ms":
                lines.append(
                    f'ray_tpu_spill_total{{node="driver",'
                    f'kind="{_escape_label(key)}"}} {int(value)}')
        lines.append("# TYPE ray_tpu_spill_restore_p50_ms gauge")
        lines.append(f"ray_tpu_spill_restore_p50_ms "
                     f"{spill.get('restore_p50_ms', 0.0)}")

        alive = sum(1 for n in runtime.gcs.list_nodes() if n.alive)
        lines.append("# TYPE ray_tpu_nodes_alive gauge")
        lines.append(f"ray_tpu_nodes_alive {alive}")

        # Same-host data-plane path split (driver side): mapped-copy
        # fetches vs leases granted on this driver's exports (daemon
        # counters live in each daemon's executor_stats).
        lines.append("# TYPE ray_tpu_same_host_copy_hits counter")
        lines.append(f"ray_tpu_same_host_copy_hits "
                     f"{getattr(runtime, 'same_host_copy_hits', 0)}")
        leases = getattr(runtime, "_export_leases", None)
        if leases is not None:
            ls = leases.stats()
            lines.append("# TYPE ray_tpu_export_map_leases gauge")
            for field in ("active", "granted", "released", "expired"):
                lines.append(
                    f'ray_tpu_export_map_leases{{state="{field}"}} '
                    f'{ls[field]}')

        lines.append("# TYPE ray_tpu_resource_available gauge")
        for key, value in runtime.cluster.available_resources().items():
            # Label VALUES take any UTF-8 (escaped); only metric names
            # need sanitizing — keep the real resource name joinable.
            lines.append(
                f'ray_tpu_resource_available'
                f'{{resource="{_escape_label(key)}"}} {value}')

        # Task events silently refused at the GCS cap: drops were
        # previously invisible — a truncated timeline looked complete.
        lines.append("# TYPE ray_tpu_task_events_dropped_total counter")
        lines.append(f"ray_tpu_task_events_dropped_total "
                     f"{runtime.gcs.task_events_dropped}")

        # Dropped trace spans (buffer cap overflow) — only meaningful
        # while tracing is armed, but always cheap to emit.
        from ray_tpu.util import tracing

        lines.append("# TYPE ray_tpu_trace_spans_dropped_total counter")
        lines.append(f"ray_tpu_trace_spans_dropped_total "
                     f"{tracing.dropped_spans()}")

        # Driver-side recovery-path counters as one labeled family
        # (node="driver" keeps them joinable with the per-node series).
        try:
            faults = runtime.fault_stats()
        except Exception:  # noqa: BLE001 — partial runtime teardown
            faults = {}
        lines.append("# TYPE ray_tpu_faults_total counter")
        for key, value in sorted(faults.items()):
            lines.append(
                f'ray_tpu_faults_total{{node="driver",'
                f'kind="{_escape_label(key)}"}} {value}')

        # Scheduler decision plane (locality hits / bytes saved / load
        # spillbacks / stale-stats skips / speculation outcomes): the
        # observability loop's own observability.
        try:
            pipeline_stats = runtime.execution_pipeline_stats()
        except Exception:  # noqa: BLE001 — partial runtime teardown
            pipeline_stats = {}
        sched = pipeline_stats.get("sched", {})
        lines.append("# TYPE ray_tpu_sched_decisions_total counter")
        for key, value in sorted(sched.items()):
            lines.append(
                f'ray_tpu_sched_decisions_total'
                f'{{kind="{_escape_label(key)}"}} {value}')

        # Driver submit-ring / dispatch-lane counters (ISSUE 15):
        # flush latency, columnar intake, lane occupancy — exported as
        # the ray_tpu_node_submit / ray_tpu_node_dispatch families
        # under node="driver" (the driver IS the node that submits),
        # keyed by the SUBMIT_STAT_KEYS / DISPATCH_STAT_KEYS
        # registries in worker.py.
        for family, group in (("ray_tpu_node_submit", "submit"),
                              ("ray_tpu_node_dispatch", "dispatch")):
            rows = pipeline_stats.get(group, {})
            lines.append(f"# TYPE {family} counter")
            for key, value in sorted(rows.items()):
                if isinstance(value, (int, float)):
                    lines.append(
                        f'{family}{{node="driver",'
                        f'key="{_escape_label(key)}"}} {int(value)}')

        # Cluster-wide per-node series: each daemon pushes its
        # executor_stats subset (pipeline / data_plane / faults) on
        # heartbeats into the GCS aggregation table; the driver folds
        # them into its scrape as labeled series — replacing the old
        # driver-only view (reference: per-node metrics agents all
        # scraped under one job in the reference deployment).
        # Durable control plane (connected mode): the head's
        # persistence counters + live incarnation epoch, fetched from
        # the GCS with a short cache so head recovery is observable
        # from any driver's scrape. Absent entirely for local-only
        # runtimes (no head to ask).
        gcs_persist = None
        try:
            gcs_persist = runtime.gcs_persist_stats()
        except Exception:  # noqa: BLE001 — partial runtime teardown
            gcs_persist = None
        if gcs_persist:
            lines.append("# TYPE ray_tpu_gcs_epoch gauge")
            lines.append(
                f"ray_tpu_gcs_epoch {gcs_persist.get('epoch', 0)}")
            lines.append(
                "# TYPE ray_tpu_gcs_snapshot_restore_ms gauge")
            lines.append(
                f"ray_tpu_gcs_snapshot_restore_ms "
                f"{gcs_persist.get('snapshot_restore_ms', 0)}")
            lines.append("# TYPE ray_tpu_gcs_persist_total counter")
            for key in ("wal_records_written", "wal_records_replayed",
                        "wal_replay_skipped", "snapshots_written",
                        "torn_wal_tails", "torn_snapshots",
                        "persist_errors", "fenced_writes"):
                lines.append(
                    f'ray_tpu_gcs_persist_total'
                    f'{{kind="{_escape_label(key)}"}} '
                    f'{gcs_persist.get(key, 0)}')
        # Sharded hot tables: one labeled gauge sample per shard per
        # GCS_SHARD_STAT_KEYS row (epoch, wal_records_replayed,
        # queued_writes, age_s, ...). Empty list when gcs_shards=1 —
        # the family only appears on sharded heads.
        gcs_shards = None
        try:
            gcs_shards = runtime.gcs_shard_stats()
        except Exception:  # noqa: BLE001 — partial runtime teardown
            gcs_shards = None
        if gcs_shards:
            from ray_tpu._private.gcs_shard import GCS_SHARD_STAT_KEYS

            lines.append("# TYPE ray_tpu_gcs_shard gauge")
            for row in gcs_shards:
                shard = row.get("shard", 0)
                for key in GCS_SHARD_STAT_KEYS:
                    lines.append(
                        f'ray_tpu_gcs_shard'
                        f'{{shard="{shard}",'
                        f'key="{_escape_label(key)}"}} '
                        f'{row.get(key, 0)}')

        # Cluster history plane: the head watchdog's typed verdicts
        # (one gauge sample per active rule/node pair — a scrape of 0
        # means the rule is known but quiet) and each node's latest
        # per-interval history sample. Absent for local-only runtimes
        # and heads predating the plane.
        health = None
        try:
            health = runtime.cluster_health()
        except Exception:  # noqa: BLE001 — partial runtime teardown
            health = None
        if health and health.get("armed"):
            lines.extend(_health_lines(health))
        history = None
        try:
            history = runtime.metrics_history(window_s=60.0)
        except Exception:  # noqa: BLE001 — partial runtime teardown
            history = None
        if history and history.get("armed"):
            lines.extend(_history_lines(history))

        by_node = _node_stats_table(runtime)
        lines.extend(_node_stat_lines(by_node))
        lines.extend(_engine_lines(by_node))
        lines.extend(_sched_node_lines(by_node))
        # Always-on performance plane: stage-latency histogram families
        # (driver's own registry + every node's heartbeat-shipped
        # snapshot) and the per-function resource attribution series.
        lines.extend(_perf_plane_lines(runtime, by_node))
        return lines

    return REGISTRY.add_collector(collect)


def _health_lines(health: dict) -> list[str]:
    """``ray_tpu_health{rule=,node=}``: 1 per ACTIVE verdict, plus a
    per-rule fired total — so a dashboard can alert on both "firing
    now" and "has fired"."""
    lines = ["# TYPE ray_tpu_health gauge"]
    for verdict in health.get("verdicts") or []:
        rule = _escape_label(str(verdict.get("rule", "")))
        node = _escape_label(str(verdict.get("node", ""))[:16])
        lines.append(
            f'ray_tpu_health{{rule="{rule}",node="{node}"}} 1')
    lines.append("# TYPE ray_tpu_health_fired_total counter")
    for rule, total in sorted(
            (health.get("fired_total") or {}).items()):
        lines.append(
            f'ray_tpu_health_fired_total'
            f'{{rule="{_escape_label(str(rule))}"}} {int(total)}')
    return lines


def _history_lines(history: dict) -> list[str]:
    """``ray_tpu_node_history{node=,key=}``: each node's latest
    per-interval delta sample out of the head's ring store (the
    windowed rates behind it ride the metrics_history RPC / ``top``;
    the scrape exports the newest interval)."""
    from ray_tpu._private.metrics_history import HISTORY_STAT_KEYS

    lines = ["# TYPE ray_tpu_node_history gauge"]
    for node_hex, row in sorted((history.get("nodes") or {}).items()):
        samples = row.get("samples") or []
        if not samples:
            continue
        latest = samples[-1]
        node = _escape_label(node_hex[:16])
        for key in HISTORY_STAT_KEYS:
            lines.append(
                f'ray_tpu_node_history{{node="{node}",'
                f'key="{_escape_label(key)}"}} '
                f'{float(latest.get(key, 0.0) or 0.0)}')
    return lines


def _node_stats_table(runtime) -> dict:
    """The GCS node-stats aggregation table ({node hex -> last pushed
    executor stats}), fetched once per scrape."""
    client = getattr(runtime, "gcs_client", None)
    if client is not None:
        try:
            return client.call("node_stats", timeout_s=2.0) or {}
        except Exception:  # noqa: BLE001 — head unreachable: skip series
            return {}
    return runtime.gcs.node_stats()


def _node_stat_lines(by_node: dict) -> list[str]:
    lines: list[str] = []
    if not by_node:
        return lines
    lines.append("# TYPE ray_tpu_node_tasks_executed counter")
    lines.append("# TYPE ray_tpu_node_running_tasks gauge")
    lines.append("# TYPE ray_tpu_node_pipeline counter")
    lines.append("# TYPE ray_tpu_node_data_plane counter")
    lines.append("# TYPE ray_tpu_node_faults counter")
    lines.append("# TYPE ray_tpu_node_spill counter")
    for node_hex, stats in sorted(by_node.items()):
        node = _escape_label(node_hex[:16])
        if not isinstance(stats, dict):
            continue
        if "tasks_executed" in stats:
            lines.append(f'ray_tpu_node_tasks_executed{{node="{node}"}} '
                         f'{stats["tasks_executed"]}')
        if "running" in stats:
            lines.append(f'ray_tpu_node_running_tasks{{node="{node}"}} '
                         f'{stats["running"]}')
        for family, metric in (("pipeline", "ray_tpu_node_pipeline"),
                               ("data_plane", "ray_tpu_node_data_plane"),
                               ("faults", "ray_tpu_node_faults"),
                               ("spill", "ray_tpu_node_spill")):
            group = stats.get(family)
            if not isinstance(group, dict):
                continue
            for key, value in sorted(group.items()):
                if isinstance(value, dict):
                    # Nested tables (lease stats) flatten one level.
                    for sub, subv in sorted(value.items()):
                        if isinstance(subv, (int, float)):
                            lines.append(
                                f'{metric}{{node="{node}",key='
                                f'"{_escape_label(f"{key}.{sub}")}"}} '
                                f'{subv}')
                    continue
                if isinstance(value, (int, float)):
                    lines.append(
                        f'{metric}{{node="{node}",'
                        f'key="{_escape_label(key)}"}} {value}')
    return lines


def _engine_lines(by_node: dict) -> list[str]:
    """LLM-engine counter family (``ray_tpu_node_engine``): engines
    hosted in THIS process surface under node="driver"; daemon-hosted
    engines arrive through the heartbeat-shipped ``engine`` stats
    group. sys.modules probe — a scrape must not import the serve tier
    into processes that never served an LLM."""
    import sys

    lines: list[str] = []
    rows: "list[tuple[str, dict]]" = []
    mod = sys.modules.get("ray_tpu.serve.llm_engine.engine")
    if mod is not None:
        merged = mod.merged_engine_stats()
        if merged:
            rows.append(("driver", merged))
    for node_hex, stats in sorted(by_node.items()):
        group = stats.get("engine") if isinstance(stats, dict) else None
        if isinstance(group, dict):
            rows.append((node_hex[:16], group))
    if not rows:
        return lines
    lines.append("# TYPE ray_tpu_node_engine counter")
    for node, group in rows:
        for key, value in sorted(group.items()):
            if isinstance(value, (int, float)):
                lines.append(
                    f'ray_tpu_node_engine{{node="{_escape_label(node)}",'
                    f'key="{_escape_label(key)}"}} {int(value)}')
    return lines


def _sched_node_lines(by_node: dict) -> list[str]:
    """Per-node load view the scheduler scores: admitted-reservation
    depth / running, the report's receipt age (stale entries decay out
    of the score past sched_stats_stale_s), and the admit/exec p50s
    from the heartbeat-shipped stage histograms."""
    from ray_tpu._private import perf_plane

    lines: list[str] = []
    if not by_node:
        return lines
    lines.append("# TYPE ray_tpu_sched_node_load gauge")
    for node_hex, stats in sorted(by_node.items()):
        if not isinstance(stats, dict):
            continue
        node = _escape_label(node_hex[:16])
        hist = stats.get("stage_hist") \
            if isinstance(stats.get("stage_hist"), dict) else {}
        rows = {
            "running": float(stats.get("running", 0.0) or 0.0),
            "depth": float(stats.get(
                "depth", stats.get("running", 0.0)) or 0.0),
            "age_s": float(stats.get("age_s", 0.0) or 0.0),
            "admit_p50_s": perf_plane.quantile(
                hist.get("admit_worker") or {}, 0.5),
            "exec_p50_s": perf_plane.quantile(
                hist.get("exec") or {}, 0.5),
        }
        for key, value in rows.items():
            lines.append(f'ray_tpu_sched_node_load{{node="{node}",'
                         f'key="{key}"}} {value:g}')
    return lines


def _hist_lines(lines: list, stage: str, node: str, snap: dict) -> None:
    """One (stage, node) histogram in Prometheus exposition form:
    cumulative ``_bucket`` lines per bound plus +Inf, ``_sum`` and
    ``_count`` (the families a real Prometheus computes p50/p99 from
    via histogram_quantile)."""
    from ray_tpu._private.perf_plane import BUCKET_BOUNDS

    counts = snap.get("counts") or []
    label = (f'stage="{_escape_label(stage)}",'
             f'node="{_escape_label(node)}"')
    cum = 0
    for i, bound in enumerate(BUCKET_BOUNDS):
        cum += int(counts[i]) if i < len(counts) else 0
        lines.append(f'ray_tpu_stage_latency_bucket{{{label},'
                     f'le="{bound:g}"}} {cum}')
    total = int(snap.get("count", 0))
    lines.append(f'ray_tpu_stage_latency_bucket{{{label},'
                 f'le="+Inf"}} {total}')
    lines.append(f'ray_tpu_stage_latency_sum{{{label}}} '
                 f'{float(snap.get("sum", 0.0)):.6f}')
    lines.append(f'ray_tpu_stage_latency_count{{{label}}} {total}')


def _perf_plane_lines(runtime, by_node: dict) -> list[str]:
    """Always-on plane families: the ``ray_tpu_stage_latency``
    histogram series labeled (stage, node) — the driver's own hops under
    node="driver", each daemon's under its node hex — and
    ``ray_tpu_task_resources`` per-function attribution (count /
    cpu-seconds / wall / peak-RSS), all recorded with tracing
    disabled."""
    from ray_tpu._private import perf_plane

    lines: list[str] = []
    if not perf_plane.PERF_ON:
        return lines
    lines.append("# TYPE ray_tpu_stage_latency histogram")
    for stage, snap in sorted(perf_plane.stage_snapshot().items()):
        _hist_lines(lines, stage, "driver", snap)
    for node_hex, stats in sorted(by_node.items()):
        hists = stats.get("stage_hist") \
            if isinstance(stats, dict) else None
        if not isinstance(hists, dict):
            continue
        for stage, snap in sorted(hists.items()):
            if isinstance(snap, dict):
                _hist_lines(lines, stage, node_hex[:16], snap)

    lines.append("# TYPE ray_tpu_task_resources gauge")

    def emit_resources(node: str, table: dict) -> None:
        for func, row in sorted(table.items()):
            if not isinstance(row, dict):
                continue
            for key in ("count", "wall_s", "cpu_s", "peak_rss_kb"):
                lines.append(
                    f'ray_tpu_task_resources{{'
                    f'node="{_escape_label(node)}",'
                    f'func="{_escape_label(func)}",'
                    f'key="{key}"}} {float(row.get(key, 0.0)):g}')

    emit_resources("driver", perf_plane.resource_snapshot())
    for node_hex, stats in sorted(by_node.items()):
        table = stats.get("task_resources") \
            if isinstance(stats, dict) else None
        if isinstance(table, dict):
            emit_resources(node_hex[:16], table)
    return lines


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0].rstrip("/")
        if path not in ("", "/metrics"):
            self.send_error(404)
            return
        body = REGISTRY.scrape().encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class MetricsAgent:
    """HTTP /metrics endpoint on a background thread."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 remove_collector=None):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._remove_collector = remove_collector
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="ray_tpu-metrics",
            daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        # Deregister the runtime collector: a later init() would otherwise
        # scrape a second (dead) runtime and emit duplicate series.
        if self._remove_collector is not None:
            self._remove_collector()
        self._server.shutdown()
        self._server.server_close()


def start_metrics_agent(runtime, port: int = 0) -> MetricsAgent:
    remove = install_runtime_collectors(runtime)
    return MetricsAgent(port=port, remove_collector=remove)
