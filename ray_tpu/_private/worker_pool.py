"""Multiprocess worker pool: OS-process task execution + process actors.

TPU-native analogue of the reference's worker pool + direct task
transport (src/ray/raylet/worker_pool.h forks language workers;
src/ray/core_worker/transport/direct_task_transport.h:75 pushes tasks to
leased workers): the driver spawns N Python worker processes, pushes
tasks over a duplex pipe with a cloudpickle serialization boundary, and
moves data through named shared-memory segments (shm_store.py) so
worker-to-worker arguments never copy through the driver.

Why processes: the thread-worker slice shares one GIL — CPU-bound
fan-out (RLlib rollouts, data preprocessing) cannot exceed one core.
Pool workers are real processes; a crashed worker is detected by pipe
EOF, the task fails with WorkerCrashedError (retryable as a system
failure, like the reference's worker-death retries), and the pool
respawns the worker.

Process actors (``ProcessActor``) give an actor a dedicated worker
process: constructor and method calls execute there in submission
order; max_restarts respawns the process and re-runs the constructor.

Nested submission: code inside a pool worker (tasks and process actors)
can call the full public API — remote()/get()/put()/wait()/actors — via
a proxy runtime that routes to the driver's client server
(worker_client.py). Blocked nested gets from TASKS release the owning
task's CPU admission through a task token, and the pool grows on demand
(up to max_size) so an outer task waiting on an inner one never starves
it. Process-actor calls carry no token — actors hold their resources
for their lifetime (and default to 0 CPU, like the reference), so
blocked actor gets keep their lease.

Process actors honor ``max_concurrency``: above 1 the pipe switches to
a multiplexed protocol (calls tagged with ids, a worker-side thread
pool, interleaved replies), so e.g. serve replicas on process actors
overlap requests AND scale past one GIL (reference: actor concurrency
groups, transport/concurrency_group_manager.h).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Any, Callable

from ray_tpu._private import perf_plane as perf
from ray_tpu._private import serialization
from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.shm_store import (
    ArenaDescriptor,
    ShmClient,
    ShmDescriptor,
    ShmDirectory,
    ShmObjectWriter,
    untrack,
)
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorError,
    TaskError,
    WorkerCrashedError,
)

# Results smaller than worker_inline_result_kb (config) ship inline
# through the pipe; mid-size ones go through the native shared arena
# (one lock round-trip, no syscalls); larger ones get a dedicated
# shared-memory segment the driver adopts (true zero-copy reads). The
# arena cutoff comes from config (object_arena_max_object_bytes) via
# the RAY_TPU_ARENA_MAX env var.


def _inline_result_bytes() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return int(GLOBAL_CONFIG.worker_inline_result_kb) * 1024


@dataclass
class _ShmRef:
    """Placeholder for an ObjectRef argument: resolved worker-side by
    mapping the segment (zero-copy)."""

    desc: ShmDescriptor


@dataclass
class _BatchTask:
    """One task of a pipelined batch run (WorkerPool.run_task_batch)."""

    idx: int                    # caller's position in the batch
    digest: str
    func_blob: bytes | None     # resolved by the caller (never None
    args_blob: bytes            # unless the worker already knows digest)
    n_returns: int
    runtime_env: dict | None = None
    token: str | None = None
    client_addr: str | None = None
    sys_path: list | None = None
    # Driver trace context (trace_id, parent span_id, anchor): rides the
    # task_seq frame so the worker stamps frame/exec times and the reply
    # carries them back. None ⇒ tracing off for this task (zero cost).
    trace: tuple | None = None
    # Absolute end-to-end deadline: rides the task_seq frame so the
    # worker refuses frames whose budget died queued behind the lease
    # head (reply status "timeout" — nothing executed).
    deadline: float | None = None
    # Driver over-subscribed this entry past the node's free slots
    # (entry flags bit 2): a failed reservation PARKS it in daemon
    # admission instead of bouncing a ("busy",) spillback.
    overcommit: bool = False
    # Return-object keys, needed daemon-side by the fused in-daemon
    # path (the worker path resolves them from the batch entries).
    return_keys: list | None = None


# --------------------------------------------------------------------------
# Worker process side
# --------------------------------------------------------------------------


def _exception_blob(exc: BaseException) -> bytes:
    tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
    try:
        return serialization.serialize_framed((exc, tb))
    except Exception:
        return serialization.serialize_framed(
            (RuntimeError(f"{type(exc).__name__}: {exc}"), tb))


class _runtime_env_ctx:
    """Apply a runtime_env around one task execution in the worker
    process (reference: python/ray/_private/runtime_env/ — per-worker
    env_vars and working_dir; our pool workers are shared, so the env
    is applied per-task and restored after)."""

    def __init__(self, runtime_env: dict | None):
        from ray_tpu._private.runtime_env_packaging import (
            resolve_runtime_env,
        )

        # Package markers ({"__pkg__": [hash, addr]}) become locally
        # extracted directories here (downloaded once per node, cached).
        self.env = resolve_runtime_env(runtime_env) or {}
        self._saved_vars: dict[str, str | None] = {}
        self._saved_cwd: str | None = None
        self._added_sys_paths: list[str] = []
        self._unload_prefixes: list[str] = []

    def _push_site(self, site: str) -> None:
        if site not in sys.path:
            sys.path.insert(0, site)
            self._added_sys_paths.append(site)
        self._unload_prefixes.append(site)

    def __enter__(self):
        try:
            self._enter_impl()
        except BaseException:
            # Partial application must not leak into the next task on
            # this shared worker (e.g. env_vars applied, then pip
            # failed): roll back what was done, then surface the error.
            self.__exit__(None, None, None)
            raise
        return self

    def _enter_impl(self):
        # Env backends FIRST (they can fail — a venv/conda error must
        # abort before any os.environ mutation): a per-hash env created
        # once per node and cached; its site-packages is prepended for
        # this task's duration and its modules unloaded after
        # (reference: runtime_env/{pip,conda}.py).
        pip_spec = self.env.get("pip")
        conda_spec = self.env.get("conda")
        if pip_spec and conda_spec:
            # Ambiguous layering (whose site-packages wins?); the
            # reference rejects the combination too. Nested pip deps
            # belong INSIDE the conda spec's dependencies.
            raise ValueError(
                "runtime_env cannot specify both 'pip' and 'conda'; "
                "put pip packages in the conda spec's dependencies "
                "({'conda': {'dependencies': [{'pip': [...]}]}})")
        if pip_spec:
            from ray_tpu._private.runtime_env_pip import ensure_pip_env

            self._push_site(ensure_pip_env(pip_spec)["site_packages"])
        if conda_spec:
            from ray_tpu._private.runtime_env_conda import (
                ensure_conda_env,
            )

            self._push_site(ensure_conda_env(conda_spec)["site_packages"])
        for k, v in (self.env.get("env_vars") or {}).items():
            self._saved_vars[k] = os.environ.get(k)
            os.environ[k] = str(v)
        working_dir = self.env.get("working_dir")
        if working_dir:
            self._saved_cwd = os.getcwd()
            os.chdir(working_dir)
            if working_dir not in sys.path:
                sys.path.insert(0, working_dir)
                self._added_sys_paths.append(working_dir)
            self._unload_prefixes.append(os.path.abspath(working_dir))
        # py_modules: local module dirs importable task-side
        # (reference: runtime_env/py_modules.py; local paths only —
        # no URI packaging without a cluster-wide store).
        for path in (self.env.get("py_modules") or []):
            abspath = os.path.abspath(path)
            parent = os.path.dirname(abspath)
            if parent not in sys.path:
                sys.path.insert(0, parent)
                self._added_sys_paths.append(parent)
            # Unload only the MODULE itself on exit, never the whole
            # parent directory (siblings may be imported legitimately
            # through other sys.path entries).
            self._unload_prefixes.append(abspath)

    def __exit__(self, *exc):
        if self._saved_cwd is not None:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass  # saved cwd may have been deleted
        if self._unload_prefixes:
            # Unload modules imported from the env's paths: pool
            # workers are shared across tasks, and a module cached in
            # sys.modules would leak into tasks without this env
            # (reference isolates via dedicated worker processes).
            dir_prefixes = tuple(p + os.sep for p in
                                 self._unload_prefixes)
            exact_files = set(self._unload_prefixes)
            for name, mod in list(sys.modules.items()):
                mod_file = getattr(mod, "__file__", None)
                if mod_file and (mod_file.startswith(dir_prefixes)
                                 or mod_file in exact_files):
                    sys.modules.pop(name, None)
        for added in self._added_sys_paths:
            try:
                sys.path.remove(added)
            except ValueError:
                pass
        for k, old in self._saved_vars.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return None


def _resolve_shm_args(args, kwargs, client: ShmClient):
    args = tuple(client.get(a.desc) if isinstance(a, _ShmRef) else a
                 for a in args)
    kwargs = {k: client.get(v.desc) if isinstance(v, _ShmRef) else v
              for k, v in kwargs.items()}
    return args, kwargs


def _pack_results(values: list, arena=None, arena_max: int = 0) -> list:
    """Each value -> ("inline", bytes) | ("arena", key, size)
    | ("shm", name, size) | ("err", blob)."""
    from multiprocessing import shared_memory

    out = []
    for value in values:
        raw = serialization.try_serialize_raw(value)
        if raw is not None:
            # Small immutable result: the raw tag encoding skips the
            # pickle round trip on both ends of the pipe.
            out.append(("inline", raw))
            continue
        try:
            header, buffers = serialization.serialize(value)
        except Exception as exc:  # noqa: BLE001 — unpicklable result
            out.append(("err", _exception_blob(exc)))
            continue
        size = serialization.framed_size(header, buffers)
        if size <= _inline_result_bytes():
            blob = bytearray(size)
            serialization.write_framed(memoryview(blob), header, buffers)
            out.append(("inline", bytes(blob)))
            continue
        if arena is not None and size <= arena_max:
            key = os.urandom(16)
            view = arena.create_for_write(key, size)
            if view is not None:
                serialization.write_framed(view, header, buffers)
                # Pinned: the driver's directory inherits the reference
                # at register_arena, so the result cannot be evicted in
                # transit. (A worker crash between here and the driver
                # receiving the reply leaks this one pin — bounded by
                # crash count, and the arena dies with the driver.)
                arena.seal_pinned(key)
                out.append(("arena", key, size))
                continue
            # Arena full even after eviction: dedicated segment below.
        seg = shared_memory.SharedMemory(create=True, size=size)
        untrack(seg)  # unlink belongs to the driver directory
        serialization.write_framed(seg.buf, header, buffers)
        name = seg.name
        seg.close()  # driver adopts + unlinks; worker drops its handle
        out.append(("shm", name, size))
    return out


def worker_main(conn) -> None:
    """Worker process entry: serve task/actor requests until exit.

    The first message is ("hello", parent_sys_path): workers adopt the
    parent's sys.path so functions pickled by reference (importable
    modules, incl. test modules) resolve.
    """
    kind, parent_sys_path = conn.recv()
    assert kind == "hello", kind
    sys.path[:0] = [p for p in parent_sys_path if p not in sys.path]
    os.environ["RAY_TPU_IN_POOL_WORKER"] = "1"  # init() guard
    client = ShmClient(untrack_on_attach=True)
    # Attach the driver's shared arena (plasma-lite) when one exists.
    arena = None
    arena_name = os.environ.get("RAY_TPU_ARENA_NAME")
    if arena_name:
        from ray_tpu._private.arena_store import ArenaStore

        arena = ArenaStore.attach(arena_name)
        client.set_arena(arena)
    arena_max = int(os.environ.get("RAY_TPU_ARENA_MAX", 1024 * 1024))
    # Flight recorder: no flusher thread (workers are many and
    # short-lived) — the ring dumps only on a fatal serve-loop error,
    # and lives in memory for lifecycle records until then.
    from ray_tpu._private import flight_recorder

    flight_recorder.install("worker")
    try:
        _serve(conn, client, arena, arena_max)
    except BaseException:
        flight_recorder.record("worker.fatal")
        flight_recorder.dump("fatal")
        raise
    finally:
        client.close_all()
        if arena is not None:
            arena.close()


def _exec_task_body(fields: tuple, func_cache: dict,
                    client: ShmClient, arena, arena_max: int,
                    stages: dict | None = None) -> list:
    """Execute one task message body (the fields after the kind/call-id
    prefix) and return the packed result descriptors. Shared by the
    classic one-in-flight ``task`` protocol and the pipelined
    ``task_seq`` protocol. ``stages`` (traced frames only) receives
    exec_start/exec_end stamps around the user function call."""
    (digest, func_blob, args_blob, n_returns, renv, token) = fields[:6]
    # Daemon pools serve many drivers: the owning driver's
    # client-server address rides with each task so nested
    # API calls reach the right owner (reference: every
    # worker knows its owner's CoreWorker address).
    client_addr = fields[6] if len(fields) > 6 else None
    if len(fields) > 7 and fields[7]:
        # Driver import paths for by-reference pickles.
        sys.path.extend(p for p in fields[7]
                        if p not in sys.path)
    if func_blob is not None:
        func = serialization.loads_function(func_blob)
        func_cache[digest] = func
    else:
        func = func_cache[digest]
    args, kwargs = serialization.deserialize_from_buffer(
        memoryview(args_blob))
    args, kwargs = _resolve_shm_args(args, kwargs, client)
    # Token rides along on nested get()/wait() RPCs so the
    # driver can release this task's CPU while it blocks.
    from ray_tpu._private import worker_client

    if client_addr:
        worker_client.set_driver_addr(client_addr)
    worker_client.set_task_token(token)
    try:
        if stages is not None:
            stages["exec_start"] = time.time()
        # Always-on attribution sample (perf_plane): cpu-seconds, wall
        # and peak-RSS delta around the user function, shipped back as
        # a 4-tuple in the stages element — the daemon/driver rolls it
        # up per function signature. Gated by the SENDER (stages is
        # only created when the owning daemon/driver asked), so a
        # runtime disarm propagates to workers with the next frame.
        sample = perf.sample_start() if stages is not None else None
        with _runtime_env_ctx(renv):
            result = func(*args, **kwargs)
        if sample is not None:
            stages["perf"] = perf.sample_end(
                getattr(func, "__qualname__", digest[:8]), sample)
        if stages is not None:
            stages["exec_end"] = time.time()
    finally:
        worker_client.set_task_token(None)
    if n_returns == 0:
        values = []
    elif n_returns == 1:
        values = [result]
    else:
        if (not isinstance(result, (tuple, list))
                or len(result) != n_returns):
            raise ValueError(
                f"task declared num_returns={n_returns} but "
                f"returned {type(result).__name__}")
        values = list(result)
    return _pack_results(values, arena, arena_max)


_jax_marked = False


def _mark_jax_if_imported() -> None:
    """Tell the fork-server template when this worker pulled jax in:
    the template (two-stage boot, worker_factory.py) watches for the
    marker and preimports jax for every LATER fork. One bool check per
    message once the marker is dropped."""
    global _jax_marked
    if _jax_marked or "jax" not in sys.modules:
        return
    _jax_marked = True
    path = os.environ.get("RAY_TPU_FACTORY_MARKER")
    if not path:
        return
    try:
        with open(path, "w"):
            pass
    except OSError:
        pass  # marker touch is advisory only


def _serve(conn, client: ShmClient, arena=None,
           arena_max: int = 0) -> None:
    actor_instance = None
    func_cache: dict[str, Any] = {}
    while True:
        _mark_jax_if_imported()
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return
        kind = msg[0]
        try:
            if kind == "exit":
                return
            elif kind == "ping":
                conn.send(("pong", os.getpid()))
            elif kind == "task":
                # Optional 10th message element: the driver's trace
                # context — stamp frame pickup + exec times and return
                # them as a third reply element (same shape as the
                # pipelined task_seq protocol). The always-on perf
                # plane rides the SAME slot as the sentinel ``False``:
                # the sender's plane is armed but tracing is not, so
                # stamp pickup + the resource sample without any trace
                # machinery (None/absent ⇒ both planes off).
                slot = msg[9] if len(msg) > 9 else None
                stages = {"worker_start": time.time(),
                          "pid": os.getpid()} \
                    if slot is not None else None
                packed = _exec_task_body(
                    msg[1:], func_cache, client, arena, arena_max,
                    stages=stages)
                conn.send(("ok", packed, stages) if stages is not None
                          else ("ok", packed))
            elif kind == "task_seq":
                # Pipelined protocol: frames arrive back-to-back (the
                # sender does not wait for replies), execute serially
                # in receive order, and each reply carries its call id
                # so the daemon-side lease matches them out of order.
                # An 11th frame element is the driver's trace context:
                # stamp frame-pickup + exec times and ship them back as
                # a 5th reply element (worker and daemon share a host,
                # so these are daemon-clock timestamps).
                call_id = msg[1]
                # 11th element: trace context, or the ``False`` perf
                # sentinel (see the "task" protocol above).
                slot = msg[10] if len(msg) > 10 else None
                traced = slot is not None
                # Optional 12th element: the absolute end-to-end
                # deadline — a frame whose budget died queued behind
                # the lease head is refused, never executed.
                deadline = msg[11] if len(msg) > 11 else None
                if deadline is not None and time.time() > deadline:
                    reply = ("task_done", call_id, "timeout", None)
                    conn.send(reply + (None,) if traced else reply)
                    continue
                stages = {"worker_start": time.time(),
                          "pid": os.getpid()} \
                    if slot is not None else None
                try:
                    packed = _exec_task_body(
                        msg[2:], func_cache, client, arena, arena_max,
                        stages=stages)
                except BaseException as exc:  # noqa: BLE001 — per-task
                    reply = ("task_done", call_id, "err",
                             _exception_blob(exc))
                    conn.send(reply + (stages,)
                              if stages is not None else reply)
                else:
                    reply = ("task_done", call_id, "ok", packed)
                    if stages is not None:
                        reply = reply + (stages,)
                    conn.send(reply)
            elif kind == "actor_new":
                _, cls_blob, args_blob, renv, max_concurrency = msg[:5]
                # Remote actors: the creating driver's sys.path entries
                # (classes pickled by reference must resolve on a daemon
                # that never saw the driver's import paths; one-machine
                # clusters share the filesystem, so the paths are valid).
                if len(msg) > 5 and msg[5]:
                    sys.path.extend(p for p in msg[5]
                                    if p not in sys.path)
                cls = serialization.loads_function(cls_blob)
                args, kwargs = serialization.deserialize_from_buffer(
                    memoryview(args_blob))
                args, kwargs = _resolve_shm_args(args, kwargs, client)
                # Actor runtime_env applies for the actor's whole life:
                # this worker process is dedicated to it.
                _runtime_env_ctx(renv).__enter__()
                actor_instance = cls(*args, **kwargs)
                conn.send(("ok", None))
                if max_concurrency and max_concurrency > 1:
                    # Switch to the multiplexed protocol: calls carry
                    # ids, execute on a thread pool, and replies
                    # interleave — the serve-replica concurrency story
                    # (reference: actor concurrency groups,
                    # transport/concurrency_group_manager.h).
                    _serve_actor_concurrent(
                        conn, actor_instance, client, arena, arena_max,
                        max_concurrency)
                    return
            elif kind == "actor_call":
                _, method_name, args_blob, n_returns = msg
                if actor_instance is None:
                    raise RuntimeError("actor_call before actor_new")
                status, payload = _invoke_actor_method(
                    actor_instance, client, arena, arena_max,
                    method_name, args_blob, n_returns)
                if status == "err":
                    conn.send(("err", payload))
                else:
                    conn.send(("ok", payload))
            else:
                raise RuntimeError(f"unknown message kind {kind!r}")
        except BaseException as exc:  # noqa: BLE001 — shipped to the driver
            try:
                conn.send(("err", _exception_blob(exc)))
            except (OSError, BrokenPipeError):
                return


def _invoke_actor_method(instance, client: ShmClient, arena,
                         arena_max: int, method_name: str,
                         args_blob: bytes, n_returns: int) -> tuple:
    """Deserialize-resolve-invoke-pack, shared by the sequential and
    multiplexed serving loops. -> ("ok", packed) | ("err", blob)."""
    try:
        args, kwargs = serialization.deserialize_from_buffer(
            memoryview(args_blob))
        args, kwargs = _resolve_shm_args(args, kwargs, client)
        method = getattr(instance, method_name)
        result = method(*args, **kwargs)
        values = [result] if n_returns == 1 else \
            (list(result) if isinstance(result, (tuple, list))
             else [None] * n_returns)
        return ("ok", _pack_results(values, arena, arena_max))
    except BaseException as exc:  # noqa: BLE001 — shipped to driver
        return ("err", _exception_blob(exc))


def _serve_actor_concurrent(conn, instance, client: ShmClient, arena,
                            arena_max: int, max_concurrency: int) -> None:
    """Multiplexed actor serving: up to ``max_concurrency`` calls run
    simultaneously on a thread pool; replies are tagged with call ids
    and interleave on the pipe (send-locked)."""
    from concurrent.futures import ThreadPoolExecutor

    send_lock = threading.Lock()
    pool = ThreadPoolExecutor(max_workers=max_concurrency,
                              thread_name_prefix="actor-call")

    def run_one(call_id, method_name, args_blob, n_returns):
        status, payload = _invoke_actor_method(
            instance, client, arena, arena_max, method_name, args_blob,
            n_returns)
        try:
            with send_lock:
                conn.send(("reply", call_id, status, payload))
        except (OSError, BrokenPipeError):
            pass  # driver gone; the process is about to exit anyway

    while True:
        _mark_jax_if_imported()
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return
        kind = msg[0]
        if kind == "exit":
            pool.shutdown(wait=False, cancel_futures=True)
            return
        if kind == "ping":
            with send_lock:
                conn.send(("pong", os.getpid()))
            continue
        if kind == "actor_call_async":
            _, call_id, method_name, args_blob, n_returns = msg
            pool.submit(run_one, call_id, method_name, args_blob,
                        n_returns)
        else:
            with send_lock:
                conn.send(("reply", msg[1] if len(msg) > 1 else -1, "err",
                           _exception_blob(RuntimeError(
                               f"unknown concurrent-actor message "
                               f"{kind!r}"))))


# --------------------------------------------------------------------------
# Driver side
# --------------------------------------------------------------------------


_factory_lock = threading.Lock()
_factory = None


def _get_factory():
    """Process-global fork-server template, started on first use (and
    restarted if it died). ~1-2s once, then every worker is a ~10ms
    fork instead of a fresh interpreter boot."""
    global _factory
    from ray_tpu._private.worker_factory import start_factory

    with _factory_lock:
        if _factory is not None and not _factory.alive():
            _factory = None
        if _factory is None:
            _factory = start_factory()
            import atexit

            atexit.register(_factory.stop)
        return _factory


def _validate_container(container: dict) -> str:
    """-> the container runtime binary; raises on a bad spec. Called
    BEFORE any listener/log-file resources exist so config errors
    (no podman on PATH, missing image) can't leak them."""
    import shutil

    runtime = container.get("runtime")
    if runtime is not None and shutil.which(runtime) is None:
        # An explicit runtime must exist too, or Popen would raise a
        # raw FileNotFoundError AFTER the listener/log resources exist.
        raise RuntimeError(
            f"runtime_env 'container' runtime {runtime!r} not on PATH")
    if runtime is None:
        runtime = next((r for r in ("podman", "docker")
                        if shutil.which(r)), None)
    if runtime is None:
        raise RuntimeError(
            "runtime_env 'container' needs podman or docker on PATH")
    if not container.get("image"):
        raise ValueError("runtime_env 'container' needs an 'image'")
    return runtime


def _container_argv(container: dict, addr: str, env: dict,
                    extra_env: dict | None = None) -> list[str]:
    """podman/docker argv for a containerized worker (reference:
    runtime_env/container.py builds `podman run` with the session dir
    and plasma socket mounted; here the connect-back socket dir and the
    framework checkout mount instead). Forwards the framework's own
    env keys PLUS every caller-supplied extra_env var (a container
    task's env_vars must be in the IN-IMAGE interpreter's env, not just
    the host-side Popen env)."""
    runtime = _validate_container(container)
    image = container["image"]
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sock_dir = os.path.dirname(addr)
    argv = [runtime, "run", "--rm", "--network=host",
            "-v", f"{sock_dir}:{sock_dir}",
            "-v", f"{pkg_root}:{pkg_root}:ro"]
    keys = ["RAY_TPU_WORKER_AUTHKEY", "PYTHONPATH",
            "RAY_TPU_DRIVER_CLIENT_ADDR", "RAY_TPU_NODE_TAG",
            "JAX_PLATFORMS", "RAY_TPU_SKIP_TPU_DETECTION"]
    keys += [k for k in (extra_env or {}) if k not in keys]
    for key in keys:
        # Bare `-e KEY`: podman/docker inherit the VALUE from the
        # Popen env, so the auth key and user secrets never appear on
        # the command line (/proc/<pid>/cmdline is world-readable).
        # `key in env` (not truthiness): an explicit empty string must
        # stay distinguishable from unset inside the image.
        if key in env:
            argv += ["-e", key]
    argv += list(container.get("run_options") or [])
    argv += [image, container.get("python", "python3"), "-m",
             "ray_tpu._private.worker_pool", addr]
    return argv


def _spawn_worker(name: str, extra_env: dict | None = None,
                  allow_tpu: bool = False,
                  container: dict | None = None):
    """Start a worker as a fresh interpreter that connects back over a
    Unix socket (reference: worker_pool.h spawns language workers that
    connect to the raylet socket).

    Fast path: fork from the pre-imported factory template
    (worker_factory.py) — worker creation cost drops from an
    interpreter boot to a fork. Fallback (TPU workers, factory
    disabled via RAY_TPU_WORKER_FACTORY_DISABLE, or factory failure):
    subprocess + connect-back (rather than multiprocessing's spawn) so
    the child never re-imports the user's ``__main__`` — unguarded user
    scripts must keep working. The child env drops accelerator plugin
    registration and pins JAX to CPU: pool workers are CPU processes.

    ``container``: a runtime_env container spec ({"image": ...,
    "run_options": [...]}) — the worker runs inside podman/docker with
    the connect-back socket dir and this checkout volume-mounted
    (reference: _private/runtime_env/container.py:26 wraps worker
    commands in `podman run`).
    """
    import secrets
    import subprocess
    import tempfile
    from multiprocessing.connection import Listener

    from ray_tpu._private.config import GLOBAL_CONFIG

    if container:
        _validate_container(container)  # raise before creating resources
    # Random suffix: concurrent spawns (e.g. several process actors
    # created back-to-back) must never race on one socket path.
    addr = os.path.join(
        tempfile.gettempdir(),
        f"ray_tpu_{os.getpid()}_{name}_{secrets.token_hex(4)}.sock")
    try:
        os.unlink(addr)
    except FileNotFoundError:
        pass
    authkey = secrets.token_bytes(16)
    listener = Listener(addr, family="AF_UNIX", authkey=authkey)
    env = dict(os.environ)
    if not allow_tpu:
        env.pop("PALLAS_AXON_POOL_IPS", None)  # skip TPU plugin registration
        env["RAY_TPU_SKIP_TPU_DETECTION"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
    env["RAY_TPU_WORKER_AUTHKEY"] = authkey.hex()
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    # The parent may have extended sys.path at runtime (e.g. a script
    # that inserted the framework's location); the child's `-m` import
    # must resolve ray_tpu before the hello handshake can deliver it.
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in sys.path if p] +
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    # Per-worker log files under the session dir (reference: worker
    # stdout/stderr files tailed by the log monitor); without a log dir
    # workers inherit the driver's console directly.
    log_dir = env.get("RAY_TPU_WORKER_LOG_DIR")
    log_path = None
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{name}.log")
    proc = None
    if container:
        argv = _container_argv(container, addr, env,
                               extra_env=extra_env)
        log_file = open(log_path, "ab") if log_path else None
        proc = subprocess.Popen(argv, env=env,
                                stdout=log_file, stderr=log_file)
        if log_file is not None:
            log_file.close()
    if proc is None and not allow_tpu \
            and not env.get("RAY_TPU_WORKER_FACTORY_DISABLE"):
        try:
            factory = _get_factory()
            # Workers whose env demands different jax/XLA import-time
            # config than the template booted with can't fork — the
            # already-imported jax would silently ignore it.
            if factory.compatible(env):
                # Fast path: a socketpair end rides SCM_RIGHTS through
                # the factory into the fork — the whole Listener/
                # accept/HMAC-challenge handshake disappears from the
                # spawn critical path.
                import socket as socket_mod
                from multiprocessing.connection import Connection

                parent_sock, child_sock = socket_mod.socketpair(
                    socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
                try:
                    proc = factory.spawn(
                        env=env, cwd=os.getcwd(), log_path=log_path,
                        pipe_fd=child_sock.fileno())
                finally:
                    child_sock.close()
                if proc is not None:
                    conn = Connection(parent_sock.detach())
                    listener.close()
                    try:
                        os.unlink(addr)
                    except FileNotFoundError:
                        pass
                    conn.send(("hello", list(sys.path)))
                    return proc, conn
        except Exception:  # noqa: BLE001 — Popen path still works
            import logging

            logging.getLogger("ray_tpu").warning(
                "worker factory unavailable; falling back to subprocess "
                "spawn", exc_info=True)
            proc = None
    if proc is None:
        log_file = open(log_path, "ab") if log_path else None
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_pool", addr],
            env=env, cwd=os.getcwd(),
            stdout=log_file, stderr=log_file)
        if log_file is not None:
            log_file.close()  # the child holds the fd now
    try:
        # Listener.accept has no timeout arg; guard with a thread join.
        conn_box: list = []

        def accept():
            try:
                conn_box.append(listener.accept())
            except Exception as exc:  # noqa: BLE001
                conn_box.append(exc)

        t = threading.Thread(target=accept, daemon=True)
        t.start()
        t.join(timeout=float(GLOBAL_CONFIG.worker_startup_timeout_s))
        if not conn_box or isinstance(conn_box[0], Exception):
            proc.kill()
            raise WorkerCrashedError(
                f"worker {name} failed to connect: "
                f"{conn_box[0] if conn_box else 'timeout'}")
        conn = conn_box[0]
    finally:
        listener.close()
        try:
            os.unlink(addr)
        except FileNotFoundError:
            pass
    conn.send(("hello", list(sys.path)))
    return proc, conn


class PoolWorker:
    """One worker process + its pipe. One in-flight request at a time."""

    def __init__(self, index: int, extra_env: dict | None = None,
                 allow_tpu: bool = False, container: dict | None = None):
        self.index = index
        self._lock = threading.Lock()
        # Function-blob digests this worker has already received (the
        # function-manager pattern: ship each function once per worker).
        self.known_digests: set[str] = set()
        self.proc, self.conn = _spawn_worker(
            f"w{index}", extra_env=extra_env, allow_tpu=allow_tpu,
            container=container)

    def request(self, msg: tuple) -> tuple:
        """Send one request and wait for its reply.

        Raises _WorkerUnavailable if the send itself fails (the request
        never reached the worker — safe to retry elsewhere), or
        WorkerCrashedError if the process dies after accepting it (the
        task may have started executing).
        """
        with self._lock:
            try:
                self.conn.send(msg)
            except (OSError, BrokenPipeError) as exc:
                raise _WorkerUnavailable(
                    f"worker {self.index} (pid {self.proc.pid}) "
                    f"unreachable: {exc!r}") from exc
            try:
                return self.conn.recv()
            except (EOFError, OSError) as exc:
                err = WorkerCrashedError(
                    f"worker {self.index} (pid "
                    f"{self.proc.pid}) died: {exc!r}")
                err.worker_pid = self.proc.pid  # OOM-kill attribution
                raise err from exc

    def send_nowait(self, msg: tuple) -> None:
        """Pipelined send: deliver one frame without waiting for its
        reply (the lease owner matches tagged replies itself). Raises
        _WorkerUnavailable when the frame never reached the worker."""
        with self._lock:
            try:
                self.conn.send(msg)
            except (OSError, BrokenPipeError, ValueError) as exc:
                raise _WorkerUnavailable(
                    f"worker {self.index} (pid {self.proc.pid}) "
                    f"unreachable: {exc!r}") from exc

    def recv_reply(self) -> tuple:
        """Pipelined receive (single reader: the lease owner). Raises
        WorkerCrashedError when the process died."""
        try:
            return self.conn.recv()
        except (EOFError, OSError) as exc:
            err = WorkerCrashedError(
                f"worker {self.index} (pid {self.proc.pid}) "
                f"died: {exc!r}")
            err.worker_pid = self.proc.pid  # OOM-kill attribution
            raise err from exc

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self) -> None:
        import subprocess

        try:
            with self._lock:
                self.conn.send(("exit",))
        except (OSError, BrokenPipeError):
            pass  # worker already dropped the pipe
        try:
            self.proc.wait(timeout=1.0)
        except subprocess.TimeoutExpired:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=1.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.conn.close()


class WorkerPool:
    """Fixed-size pool of task workers (reference: worker_pool.h pops a
    worker per lease, returns it after; prestart keeps latency low)."""

    def __init__(self, size: int, directory: ShmDirectory,
                 driver_client: ShmClient, max_size: int | None = None):
        self.size = size
        # Growth headroom for nested submission: an outer task blocked in
        # get() occupies its worker while the nested task needs another
        # (reference: the raylet starts workers on demand; CPU admission,
        # not pool size, bounds running tasks).
        self.max_size = max_size if max_size is not None else size * 4 + 8
        # How many workers to KEEP between tasks. A lazy pool (size=0,
        # no prestart) must still retain its grown workers — retiring
        # every worker at release makes every task pay a full process
        # spawn (observed: ~235ms/task vs ~1ms with a warm worker).
        self.idle_cap = size if size > 0 else min(4, self.max_size)
        self.directory = directory
        self.driver_client = driver_client
        self._lock = threading.Condition(threading.Lock())
        self._index_lock = threading.Lock()
        self._idle: list[PoolWorker] = []
        self._all_workers: set[PoolWorker] = set()
        self._next_index = 0
        self._num_leased = 0
        self._shutdown = False
        # Pipelined-batch counters (executor_stats drain stages).
        self._batch_lock = threading.Lock()
        self.batch_runs = 0     # multi-task lease runs
        self.batch_tasks = 0    # tasks entering run_task_batch
        self.batch_frames = 0   # pipelined frames actually sent
        self.batch_requeues = 0  # unstarted frames requeued (crashes)
        # Spawn in parallel: each worker blocks on interpreter boot +
        # socket handshake, so serial startup would be O(N).
        # size=0 is a legal lazy pool — no prestart, growth on demand
        # (many-node single-box clusters boot O(N) daemons; paying a
        # worker spawn per daemon up front is pure wasted wall-clock).
        from concurrent.futures import ThreadPoolExecutor

        if size <= 0:
            return
        with ThreadPoolExecutor(max_workers=min(size, 8)) as tpe:
            self._idle.extend(tpe.map(lambda _: self._new_worker(),
                                      range(size)))

    @staticmethod
    def _import_sensitive_env_vars(runtime_env: dict | None) -> dict:
        if not runtime_env:
            return {}
        from ray_tpu._private.worker_factory import (
            import_sensitive_subset,
        )

        return import_sensitive_subset(
            {str(k): str(v)
             for k, v in (runtime_env.get("env_vars") or {}).items()})

    def _new_worker(self, extra_env: dict | None = None,
                    container: dict | None = None) -> PoolWorker:
        with self._index_lock:
            index = self._next_index
            self._next_index += 1
        worker = PoolWorker(index, extra_env=extra_env,
                            container=container)
        with self._index_lock:
            self._all_workers.add(worker)
            self._all_workers = {w for w in self._all_workers
                                 if w.alive()}
        return worker

    def live_workers(self) -> list[PoolWorker]:
        """All live workers, idle or busy (memory-monitor view)."""
        with self._index_lock:
            return [w for w in self._all_workers if w.alive()]

    def _acquire(self) -> PoolWorker:
        grow = False
        with self._lock:
            while not self._idle and not self._shutdown:
                # Grow past `size` (up to max_size) instead of waiting:
                # every leased worker may be an outer task blocked on a
                # nested one that needs a worker of its own.
                if self._num_leased < self.max_size:
                    self._num_leased += 1
                    grow = True
                    break
                self._lock.wait(timeout=0.5)
            if self._shutdown:
                raise RuntimeError("worker pool is shut down")
            if not grow:
                worker = self._idle.pop()
                self._num_leased += 1
        if grow:
            try:
                return self._new_worker()
            except BaseException:
                # Give the lease slot back, or a failed spawn (e.g.
                # fork under memory pressure) pins the pool at max_size.
                with self._lock:
                    self._num_leased -= 1
                    self._lock.notify()
                raise
        if worker.alive():
            return worker
        # Died while idle (crash, memory-monitor kill): replace it
        # (spawn happens outside the condition lock — it is slow).
        worker.stop()
        try:
            return self._new_worker()
        except BaseException:
            with self._lock:
                self._num_leased -= 1
                self._lock.notify()
            raise

    def _release(self, worker: PoolWorker) -> None:
        # Spawn any replacement outside the pool lock (spawn is slow and
        # _new_worker must not nest under the condition lock).
        replacement = None
        if not worker.alive():
            if self._num_leased <= self.size:
                replacement = self._new_worker()
            else:
                worker.stop()  # shrink back toward the target size
        with self._lock:
            self._num_leased -= 1
            if self._shutdown:
                worker.stop()
                if replacement is not None:
                    replacement.stop()
                return
            if replacement is not None:
                self._idle.append(replacement)
            elif worker.alive():
                if len(self._idle) < self.idle_cap:
                    self._idle.append(worker)
                else:
                    # Surplus growth worker: retire it now that the
                    # burst is over (stop() can block; do it off-lock).
                    threading.Thread(target=worker.stop,
                                     daemon=True).start()
            self._lock.notify()

    # ----------------------------------------------------- pipelined batches

    def try_acquire_idle(self) -> "PoolWorker | None":
        """Non-blocking lease of an IDLE worker: never grows the pool,
        never waits (opportunistic extra lease runners for a batch)."""
        with self._lock:
            if self._shutdown:
                return None
            while self._idle:
                worker = self._idle.pop()
                if worker.alive():
                    self._num_leased += 1
                    return worker
                worker.stop()
        return None

    def run_task_batch(self, tasks: "list[_BatchTask]", on_result,
                       depth: int, tracker=None) -> None:
        """Execute a batch over pipelined multi-task worker leases.

        One blocking lease is taken up front; whenever a runner's
        pipeline is full (or deeper than the remaining queue) and tasks
        are still queued, IDLE workers are leased opportunistically —
        short tasks drain through one amortized lease, long tasks fan
        out across workers. Each lease keeps up to ``depth`` call-id-
        tagged frames in flight (the acquire/release and the function-
        digest check are paid once per run, not once per task).

        ``on_result(task, status, payload)`` fires exactly once per
        task from runner threads: status is "ok" (packed descriptors),
        "err" (exception blob) or "crash" (WorkerCrashedError — the
        task may have started). Worker death mid-pipeline fails ONLY
        the oldest in-flight frame; the rest were never started and are
        requeued onto a fresh lease.

        ``tracker`` (optional) observes lease composition for
        blocked-head parking: sent(key, token), done(key, token),
        drop_lease(key).
        """
        from collections import deque

        if not tasks:
            return
        state = _BatchState(deque(tasks), on_result, max(1, depth),
                            tracker, len(tasks))
        with self._batch_lock:
            self.batch_runs += 1
            self.batch_tasks += len(tasks)
        worker = self._acquire()
        self._batch_runner(worker, state)
        # The primary runner returned (queue empty, its frames done);
        # sibling runners may still hold in-flight frames.
        state.done.wait()

    def _maybe_extra_runner(self, state: "_BatchState") -> None:
        with state.lock:
            if not state.queue:
                return
        worker = self.try_acquire_idle()
        if worker is None:
            return
        threading.Thread(target=self._batch_runner,
                         args=(worker, state), daemon=True,
                         name="pool-batch-lease").start()

    def _batch_runner(self, worker: "PoolWorker",
                      state: "_BatchState") -> None:
        from collections import deque

        tracker = state.tracker
        while True:  # one iteration per lease (worker replaced on crash)
            lease_key = object()
            inflight: deque = deque()  # (call_id, task)
            next_id = 0
            crashed: BaseException | None = None
            while True:
                while len(inflight) < state.depth:
                    with state.lock:
                        task = (state.queue.popleft()
                                if state.queue else None)
                    if task is None:
                        break
                    blob = (None if task.digest in worker.known_digests
                            else task.func_blob)
                    next_id += 1
                    frame = ("task_seq", next_id, task.digest, blob,
                             task.args_blob, task.n_returns,
                             task.runtime_env, task.token,
                             task.client_addr,
                             task.sys_path if blob is not None
                             else None)
                    # Trace context, or the False perf-plane sentinel
                    # (this process's gate — workers follow the sender
                    # so a runtime disarm takes effect frame-by-frame).
                    slot = task.trace if task.trace is not None \
                        else (False if perf.PERF_ON else None)
                    if slot is not None or task.deadline is not None:
                        # Optional 11th/12th elements: trace/perf slot
                        # and the absolute deadline (absent on both ⇒
                        # the plain frame shape, byte-identical).
                        frame = frame + (slot,)
                    if task.deadline is not None:
                        frame = frame + (task.deadline,)
                    try:
                        worker.send_nowait(frame)
                    except _WorkerUnavailable as exc:
                        # Never delivered: this task is retryable as
                        # unstarted alongside the queued in-flight ones.
                        with state.lock:
                            state.queue.appendleft(task)
                        with self._batch_lock:
                            self.batch_requeues += 1
                        crashed = exc
                        break
                    worker.known_digests.add(task.digest)
                    inflight.append((next_id, task))
                    with self._batch_lock:
                        self.batch_frames += 1
                    if tracker is not None and task.token:
                        tracker.sent(lease_key, task.token)
                if crashed is not None:
                    break
                if not inflight:
                    self._release(worker)
                    return
                with state.lock:
                    more = bool(state.queue)
                if more:
                    self._maybe_extra_runner(state)
                try:
                    msg = worker.recv_reply()
                except WorkerCrashedError as exc:
                    crashed = exc
                    break
                if msg[0] != "task_done":
                    continue  # stray classic-protocol frame
                call_id, status, payload = msg[1], msg[2], msg[3]
                # Traced frames carry the worker's stage stamps as a
                # 5th element (frame pickup + exec start/end).
                wtrace = msg[4] if len(msg) > 4 else None
                task = None
                for i, (cid, t) in enumerate(inflight):
                    if cid == call_id:
                        task = t
                        del inflight[i]
                        break
                if task is None:
                    continue
                if tracker is not None and task.token:
                    tracker.done(lease_key, task.token)
                self._complete_one(state, task, status, payload, wtrace)
            # Worker died (or refused the frame). The OLDEST in-flight
            # frame was executing — it may have side effects, so it
            # fails; everything behind it never started and is retried
            # on a fresh lease.
            if tracker is not None:
                tracker.drop_lease(lease_key)
            started = inflight.popleft() if inflight else None
            if started is not None:
                self._complete_one(state, started[1], "crash", crashed)
            if inflight:
                with self._batch_lock:
                    self.batch_requeues += len(inflight)
            with state.lock:
                state.queue.extendleft(t for _, t in reversed(inflight))
                remaining = bool(state.queue)
            self._release(worker)
            if not remaining:
                return
            try:
                worker = self._acquire()
            except BaseException:  # noqa: BLE001 — pool shut down
                with state.lock:
                    stranded = list(state.queue)
                    state.queue.clear()
                for task in stranded:
                    self._complete_one(state, task, "crash", crashed)
                return

    def _complete_one(self, state: "_BatchState", task: "_BatchTask",
                      status: str, payload, wtrace=None) -> None:
        try:
            state.on_result(task, status, payload, wtrace)
        finally:
            with state.lock:
                state.remaining -= 1
                if state.remaining <= 0:
                    state.done.set()

    # ------------------------------------------------------------- task path

    def marshal_args(self, args: tuple, kwargs: dict,
                     promote: Callable[[Any], ShmDescriptor]) -> bytes:
        """Replace top-level ObjectRef args with _ShmRef descriptors
        (promoting driver-held values into shm) and frame the rest."""
        from ray_tpu._private.object_ref import ObjectRef

        if not any(isinstance(a, ObjectRef) for a in args) \
                and not any(isinstance(v, ObjectRef)
                            for v in kwargs.values()):
            # Ref-free small-immutable calls skip the pickle round trip
            # (the worker's deserialize dispatches on the raw sentinel).
            raw = serialization.try_serialize_raw((args, kwargs))
            if raw is not None:
                return raw

        def convert(a):
            if isinstance(a, ObjectRef):
                return _ShmRef(promote(a))
            return a

        conv_args = tuple(convert(a) for a in args)
        conv_kwargs = {k: convert(v) for k, v in kwargs.items()}
        return serialization.serialize_framed((conv_args, conv_kwargs))

    def run_task_blobs(self, digest: str, func_blob: bytes, args_blob: bytes,
                       n_returns: int, return_ids: list[ObjectID],
                       runtime_env: dict | None = None,
                       task_token: str | None = None,
                       client_addr: str | None = None,
                       sys_path: list | None = None,
                       trace: tuple | None = None,
                       stages_out: dict | None = None,
                       ) -> list[tuple[ObjectID, Any]]:
        """Execute on a pool worker; returns [(return_id, value)] pairs.

        ``trace`` arms worker-side stage stamping for this task;
        ``stages_out`` (a dict) receives the worker's frame/exec
        timestamps from the reply.

        The function blob only crosses the pipe the first time a given
        worker sees its digest (function-manager pattern); afterwards
        the worker's cache is addressed by digest alone.

        Raises WorkerCrashedError (system failure) or _RemoteTaskError
        (application failure, carrying the remote traceback). A worker
        that proves unreachable before accepting the request is replaced
        and the request retried on another — no work was started, so
        this is invisible to the caller.
        """
        sensitive = self._import_sensitive_env_vars(runtime_env)
        container = (runtime_env or {}).get("container")
        if sensitive or container:
            # jax/XLA read these at IMPORT time; a shared worker (and
            # any fork of the pre-imported factory template) has jax
            # frozen already, so per-task os.environ application would
            # be silently ignored. Such tasks — and container tasks,
            # whose interpreter must boot INSIDE the image — get a
            # dedicated fresh worker whose spawn env carries the vars,
            # under the SAME lease accounting as the shared pool, so N
            # in-flight env-sensitive tasks still respect max_size (and
            # a shut-down pool refuses them).
            with self._lock:
                while self._num_leased >= self.max_size \
                        and not self._shutdown:
                    self._lock.wait(timeout=0.5)
                if self._shutdown:
                    raise RuntimeError("worker pool is shut down")
                self._num_leased += 1
            worker = None
            try:
                worker = self._new_worker(
                    extra_env=dict(runtime_env.get("env_vars") or {}),
                    container=container)
                msg = ("task", digest, func_blob, args_blob, n_returns,
                       runtime_env, task_token, client_addr, sys_path)
                slot = trace if trace is not None \
                    else (False if perf.PERF_ON else None)
                if slot is not None:
                    msg = msg + (slot,)
                reply = worker.request(msg)
                self._copy_reply_stages(reply, stages_out)
                return self._unpack_reply(reply, return_ids)
            finally:
                if worker is not None:
                    worker.stop()
                    with self._index_lock:
                        self._all_workers.discard(worker)
                with self._lock:
                    self._num_leased -= 1
                    self._lock.notify()
        while True:
            worker = self._acquire()
            send_blob = None if digest in worker.known_digests else func_blob
            msg = ("task", digest, send_blob, args_blob, n_returns,
                   runtime_env, task_token, client_addr,
                   sys_path if send_blob is not None else None)
            slot = trace if trace is not None \
                else (False if perf.PERF_ON else None)
            if slot is not None:
                msg = msg + (slot,)
            try:
                reply = worker.request(msg)
            except _WorkerUnavailable:
                continue  # _release (in finally) already spawns a live one
            finally:
                self._release(worker)
            worker.known_digests.add(digest)
            self._copy_reply_stages(reply, stages_out)
            return self._unpack_reply(reply, return_ids)

    @staticmethod
    def _copy_reply_stages(reply: tuple, stages_out: dict | None) -> None:
        if stages_out is not None and len(reply) > 2 and reply[2]:
            stages_out.update(reply[2])

    def _unpack_reply(self, reply: tuple,
                      return_ids: list[ObjectID]) -> list[tuple[ObjectID, Any]]:
        if reply[0] == "err":
            exc, tb = serialization.deserialize_from_buffer(
                memoryview(reply[1]))
            raise _RemoteTaskError(exc, tb)
        results = []
        for rid, packed in zip(return_ids, reply[1]):
            if packed[0] == "inline":
                value = serialization.deserialize_from_buffer(
                    memoryview(packed[1]))
            elif packed[0] == "arena":
                desc = ArenaDescriptor(packed[1], packed[2])
                self.directory.register_arena(rid, desc)
                value = self.driver_client.get(desc)
            elif packed[0] == "shm":
                desc = ShmDescriptor(packed[1], packed[2])
                self.directory.adopt(rid, desc)
                value = self.driver_client.get(desc)
            else:  # ("err", blob) — this return value failed to pickle
                exc, tb = serialization.deserialize_from_buffer(
                    memoryview(packed[1]))
                raise _RemoteTaskError(exc, tb)
            results.append((rid, value))
        return results

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            workers = list(self._idle)
            self._idle.clear()
            self._lock.notify_all()
        for w in workers:
            w.stop()


class _BatchState:
    """Shared state of one run_task_batch call: the task queue lease
    runners pull from, completion accounting, and the parking
    tracker."""

    __slots__ = ("queue", "on_result", "depth", "tracker", "remaining",
                 "lock", "done")

    def __init__(self, queue, on_result, depth, tracker, n):
        self.queue = queue
        self.on_result = on_result
        self.depth = depth
        self.tracker = tracker
        self.remaining = n
        self.lock = threading.Lock()
        self.done = threading.Event()


class _RemoteTaskError(Exception):
    """Carries a worker-side exception + its remote traceback string."""

    def __init__(self, cause: BaseException, remote_tb: str):
        super().__init__(str(cause))
        self.cause = cause
        self.remote_tb = remote_tb


class _WorkerUnavailable(Exception):
    """The request could not be delivered (worker already dead)."""


# --------------------------------------------------------------------------
# Process actors
# --------------------------------------------------------------------------


class ProcessActor:
    """An actor bound to a dedicated worker process.

    Mirrors LocalActor's interface (submit/kill/is_dead) so the Runtime
    treats both uniformly; calls execute in submission order in the
    worker process (reference: a Ray actor IS a worker process with an
    ordered scheduling queue, transport/actor_scheduling_queue.h).
    """

    def __init__(self, actor_id: ActorID, cls: type, init_args: tuple,
                 init_kwargs: dict, runtime, *, max_restarts: int = 0,
                 max_pending_calls: int = -1,
                 max_concurrency: int = 1,
                 creation_return_id: ObjectID | None = None,
                 on_death: Callable[[ActorID, str], None] | None = None,
                 on_restart: Callable[[ActorID], None] | None = None,
                 runtime_env: dict | None = None):
        import queue as queue_mod

        self.actor_id = actor_id
        self._cls = cls
        self._max_concurrency = max(1, int(max_concurrency))
        self._runtime_env = runtime_env
        self._init_args = init_args
        self._init_kwargs = init_kwargs
        self._runtime = runtime
        self._max_restarts = max_restarts
        self._max_pending_calls = max_pending_calls
        self._on_death = on_death
        self._on_restart = on_restart
        self._num_restarts = 0
        self._queue: queue_mod.Queue = queue_mod.Queue()
        self._pending = 0
        self._lock = threading.Lock()
        self._dead = False
        self._death_reason: str | None = None
        self._creation_return_id = creation_return_id
        self._worker: PoolWorker | None = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"ray_tpu-pactor-{cls.__name__}")
        self._thread.start()

    # Interface shared with LocalActor ------------------------------------

    def submit(self, call) -> None:
        from ray_tpu.exceptions import PendingCallsLimitExceeded

        with self._lock:
            if self._dead:
                self._fail_call(call, ActorDiedError(
                    self.actor_id, self._death_reason or "actor has died"))
                return
            if 0 <= self._max_pending_calls <= self._pending:
                self._fail_call(call, PendingCallsLimitExceeded(
                    f"actor {self._cls.__name__} has {self._pending} "
                    f"pending calls"))
                return
            self._pending += 1
            self._queue.put(call)

    def kill(self, reason: str = "killed via kill()",
             no_restart: bool = True) -> None:
        restartable = (not no_restart) and self._num_restarts < self._max_restarts
        # Terminate the process FIRST: an in-flight request holds the
        # PoolWorker lock until its recv fails, and _mark_dead's
        # worker.stop() needs that lock — killing after would deadlock.
        worker = self._worker
        if worker is not None and worker.alive():
            worker.proc.terminate()
        self._mark_dead(reason, notify=not restartable)
        self._queue.put(None)
        if restartable:
            self._restart()

    def is_dead(self) -> bool:
        with self._lock:
            return self._dead

    def wait_started(self, timeout: float | None = None) -> bool:
        return self._started.wait(timeout)

    # Internals ------------------------------------------------------------

    def _fail_call(self, call, error: BaseException) -> None:
        for rid in call.return_ids:
            self._runtime.store.put_error(rid, error)

    def _marshal(self, args: tuple, kwargs: dict) -> bytes:
        return serialization.serialize_framed((args, kwargs))

    def _run(self) -> None:
        try:
            self._worker = PoolWorker(-1)
            record = getattr(self, "_gcs_record", None)
            if record is not None:
                # Actor-table placement: the dedicated process's pid
                # (also corrects the stale pid after a restart respawn).
                record.pid = self._worker.proc.pid
            cls_blob = serialization.dumps_function(self._cls)
            args_blob = self._marshal(self._init_args, self._init_kwargs)
            reply = self._worker.request(
                ("actor_new", cls_blob, args_blob, self._runtime_env,
                 self._max_concurrency))
            if reply[0] == "err":
                exc, tb = serialization.deserialize_from_buffer(
                    memoryview(reply[1]))
                raise ActorError(exc, tb, f"{self._cls.__name__}.__init__")
        except BaseException as exc:  # noqa: BLE001
            self._mark_dead(f"constructor failed: {exc!r}")
            if self._creation_return_id is not None:
                self._runtime.store.put_error(self._creation_return_id, exc)
            return
        if self._creation_return_id is not None:
            self._runtime.store.put(self._creation_return_id, None)
        self._started.set()
        if self._max_concurrency > 1:
            self._run_concurrent()
            return
        while True:
            call = self._queue.get()
            if call is None:
                return
            try:
                with self._lock:
                    self._pending -= 1
                    if self._dead:
                        self._fail_call(call, ActorDiedError(
                            self.actor_id,
                            self._death_reason or "actor died"))
                        continue
                from ray_tpu._private.actor_runtime import (
                    _call_deadline_error,
                )

                expired = _call_deadline_error(call, self._cls.__name__)
                if expired is not None:
                    self._fail_call(call, expired)
                    continue
                try:
                    args_blob = self._marshal(call.args, call.kwargs)
                except Exception as exc:  # noqa: BLE001 — unpicklable args
                    self._fail_call(call, ActorError(
                        exc, "", f"{self._cls.__name__}.{call.method_name} "
                        f"(argument serialization)"))
                    continue
                try:
                    reply = self._worker.request(
                        ("actor_call", call.method_name, args_blob,
                         len(call.return_ids)))
                    if reply[0] == "err":
                        exc, tb = serialization.deserialize_from_buffer(
                            memoryview(reply[1]))
                        self._fail_call(call, ActorError(
                            exc, tb,
                            f"{self._cls.__name__}.{call.method_name}"))
                        continue
                    self._store_call_results(call, reply[1])
                except (WorkerCrashedError, _WorkerUnavailable):
                    self._handle_crash(call)
                    return
                except BaseException as exc:  # noqa: BLE001 — never kill
                    # the executor thread silently: fail the call and
                    # keep serving.
                    self._fail_call(call, exc)
            finally:
                # Unbind before re-blocking in get(): a stale frame
                # local would keep the last call's args (and nested
                # ObjectRefs) alive until the next call arrives.
                call = None

    def _store_call_results(self, call, packed_list) -> None:
        for rid, packed in zip(call.return_ids, packed_list):
            if packed[0] == "inline":
                value = serialization.deserialize_from_buffer(
                    memoryview(packed[1]))
            elif packed[0] == "arena":
                desc = ArenaDescriptor(packed[1], packed[2])
                self._runtime.shm_directory.register_arena(rid, desc)
                value = self._runtime.shm_client.get(desc)
            elif packed[0] == "shm":
                desc = ShmDescriptor(packed[1], packed[2])
                self._runtime.shm_directory.adopt(rid, desc)
                value = self._runtime.shm_client.get(desc)
            else:  # ("err", blob): this return value failed to pickle
                exc, tb = serialization.deserialize_from_buffer(
                    memoryview(packed[1]))
                self._fail_call(call, ActorError(
                    exc, tb, f"{self._cls.__name__}.{call.method_name}"))
                return
            self._runtime.store.put(rid, value)

    def _run_concurrent(self) -> None:
        """Multiplexed mode (max_concurrency > 1): submissions stream to
        the worker tagged with call ids, a reader thread matches
        interleaved replies, and up to max_concurrency calls execute
        simultaneously worker-side. Per-caller ordering is NOT
        guaranteed — the same trade the reference makes for
        max_concurrency > 1 actors."""
        worker = self._worker
        # Generation guard: _restart bumps _num_restarts BEFORE spawning
        # the replacement thread, so comparing it is race-free (checking
        # self._worker is not — it's replaced only after the slow
        # process spawn completes, leaving a window where a stale sender
        # could steal a post-restart call).
        my_gen = self._num_restarts
        conn = worker.conn
        send_lock = threading.Lock()
        pending: dict[int, Any] = {}
        pending_lock = threading.Lock()
        next_id = [0]

        def reader():
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    break
                if msg[0] != "reply":
                    continue
                _, call_id, status, payload = msg
                with pending_lock:
                    call = pending.pop(call_id, None)
                if call is None:
                    continue
                with self._lock:
                    # _pending counts queued + in-flight here, so
                    # max_pending_calls bounds the true outstanding work
                    # (decrement only once the reply landed).
                    self._pending = max(0, self._pending - 1)
                # The reader must never die silently: one bad reply
                # (shm attach failure, undeserializable payload) fails
                # ITS call and the loop keeps serving — otherwise every
                # in-flight call hangs forever with the pipe still open.
                try:
                    if status == "err":
                        exc, tb = serialization.deserialize_from_buffer(
                            memoryview(payload))
                        self._fail_call(call, ActorError(
                            exc, tb,
                            f"{self._cls.__name__}.{call.method_name}"))
                    else:
                        self._store_call_results(call, payload)
                except BaseException as exc:  # noqa: BLE001
                    self._fail_call(call, exc)
            # Pipe closed: fail everything still in flight. The reader
            # is the single authority for crash handling in concurrent
            # mode (the sender defers to it); skip if this worker
            # generation was already replaced or cleanly killed.
            with pending_lock:
                stranded = list(pending.values())
                pending.clear()
            for call in stranded:
                self._fail_call(call, ActorDiedError(
                    self.actor_id, "actor process died with calls "
                    "in flight"))
            if self._num_restarts == my_gen and not self.is_dead():
                restartable = self._num_restarts < self._max_restarts
                self._mark_dead("actor process died",
                                notify=not restartable)
                if restartable:
                    self._restart()

        reader_thread = threading.Thread(
            target=reader, daemon=True,
            name=f"ray_tpu-pactor-read-{self._cls.__name__}")
        reader_thread.start()

        while True:
            call = self._queue.get()
            if call is None:
                return
            if self._num_restarts != my_gen:
                # A crash-restart replaced this generation while we were
                # blocked on the queue: hand the call to the NEW
                # sender and exit (stale senders must not steal work).
                self._queue.put(call)
                return
            with self._lock:
                # NOTE: _pending is NOT decremented here — it keeps
                # counting until the reply arrives (reader thread), so
                # max_pending_calls bounds queued + in-flight.
                if self._dead:
                    self._pending = max(0, self._pending - 1)
                    self._fail_call(call, ActorDiedError(
                        self.actor_id, self._death_reason or "actor died"))
                    continue
            from ray_tpu._private.actor_runtime import (
                _call_deadline_error,
            )

            expired = _call_deadline_error(call, self._cls.__name__)
            if expired is not None:
                with self._lock:
                    self._pending = max(0, self._pending - 1)
                self._fail_call(call, expired)
                continue
            try:
                args_blob = self._marshal(call.args, call.kwargs)
            except Exception as exc:  # noqa: BLE001 — unpicklable args
                with self._lock:
                    self._pending = max(0, self._pending - 1)
                self._fail_call(call, ActorError(
                    exc, "", f"{self._cls.__name__}.{call.method_name} "
                    f"(argument serialization)"))
                continue
            call_id = next_id[0]
            next_id[0] += 1
            with pending_lock:
                pending[call_id] = call
            try:
                with send_lock:
                    conn.send(("actor_call_async", call_id,
                               call.method_name, args_blob,
                               len(call.return_ids)))
            except (OSError, BrokenPipeError):
                with pending_lock:
                    pending.pop(call_id, None)
                with self._lock:
                    self._pending = max(0, self._pending - 1)
                # Fail this call; death/restart is the READER's job
                # (single authority — two restart paths would race).
                self._fail_call(call, ActorDiedError(
                    self.actor_id,
                    f"actor process died sending {call.method_name}()"))
                return
            # Unbind before re-blocking (pending holds the call until
            # the reader delivers its reply; the stale frame local
            # would extend that past delivery).
            call = None
            args_blob = None

    def _handle_crash(self, call) -> None:
        reason = f"actor process died executing {call.method_name}()"
        restartable = self._num_restarts < self._max_restarts
        self._fail_call(call, ActorDiedError(self.actor_id, reason))
        self._mark_dead(reason, notify=not restartable)
        if restartable:
            self._restart()

    def _mark_dead(self, reason: str, notify: bool = True) -> None:
        import queue as queue_mod

        with self._lock:
            if self._dead:
                return
            self._dead = True
            self._death_reason = reason
            drained = []
            try:
                while True:
                    item = self._queue.get_nowait()
                    if item is not None:
                        drained.append(item)
            except queue_mod.Empty:
                pass
            self._pending = 0
        for call in drained:
            self._fail_call(call, ActorDiedError(self.actor_id, reason))
        worker = self._worker
        if worker is not None:
            worker.stop()
        if notify and self._on_death is not None:
            self._on_death(self.actor_id, reason)

    def _restart(self) -> None:
        with self._lock:
            self._num_restarts += 1
            self._dead = False
            self._death_reason = None
        self._started.clear()
        self._creation_return_id = None
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"ray_tpu-pactor-{self._cls.__name__}-r{self._num_restarts}")
        self._thread.start()
        if self._on_restart is not None:
            self._on_restart(self.actor_id)


# --------------------------------------------------------------------------
# Worker executable entry: python -m ray_tpu._private.worker_pool <socket>
# --------------------------------------------------------------------------

if __name__ == "__main__":
    from multiprocessing.connection import Client

    # Serve from the canonically-imported module, not this __main__
    # alias: unpickled _ShmRef instances come from the import-path copy
    # and must be the same class the serving loop isinstance-checks.
    from ray_tpu._private.worker_pool import worker_main as _worker_main

    _addr = sys.argv[1]
    _authkey = bytes.fromhex(os.environ.pop("RAY_TPU_WORKER_AUTHKEY"))
    _conn = Client(_addr, family="AF_UNIX", authkey=_authkey)
    _worker_main(_conn)
