"""Same-host zero-copy object plane — shared pieces.

When two daemons (or a daemon and the driver) share a host, a fetch
does not need to move bytes through the RPC transport at all: the
holder's copy already lives in named POSIX shared memory (a dedicated
segment or the native arena, _native/plasma_store.cpp), and the puller
can map it directly (reference: plasma is host-shared by design —
src/ray/object_manager/plasma/store_runner.h; one store serves every
worker on the node).

Three pieces live here, used by both the node executor and the
driver's export server:

- ``host_identity()``: a durable host id (boot-id based, NOT the IP —
  many daemons share one IP on a test box, and one host can have many
  addresses). Published through GCS node registration and echoed in
  ``fetch_plan`` replies so a puller can recognize a co-hosted holder.
- ``LeaseTable``: the owner-side pin registry. A holder that maps (or
  copies from) a peer's shared memory takes a lease first; the owner
  pins the underlying object (arena refcount / segment reference) for
  the lease's life, so eviction or reuse cannot invalidate the
  mapping. Leases are released explicitly (``unpin_object``) or swept
  when they outlive the TTL AND their holder stopped answering pings —
  a dead puller cannot pin an object forever.
- ``PeerArenaRegistry``: cached read-only attachments to other
  processes' arenas (ArenaStore.attach — the same mechanism pool
  workers already use), keyed by arena name.

Map sources cross the RPC boundary as plain dicts (pickle-friendly):
``{"kind": "seg"|"arena", "name": ..., "key": ..., "size": ...,
"host": ..., "token": ...}``.
"""

from __future__ import annotations

import os
import threading

from ray_tpu._private import lock_witness
import time
from typing import Callable

_HOST_ID: str | None = None
_HOST_ID_LOCK = lock_witness.Lock("same_host.HOST_ID")


def host_identity() -> str:
    """Durable identity of this host (stable across processes, changes
    on reboot). ``RAY_TPU_HOST_ID`` overrides — tests use it to
    simulate cross-host daemons on one box."""
    global _HOST_ID
    override = os.environ.get("RAY_TPU_HOST_ID")
    if override:
        return override
    with _HOST_ID_LOCK:
        if _HOST_ID is None:
            boot_id = ""
            try:
                with open("/proc/sys/kernel/random/boot_id") as f:
                    boot_id = f.read().strip()
            except OSError:
                pass  # no /proc boot_id: fallback below
            if not boot_id:
                import socket
                import uuid

                boot_id = f"{socket.gethostname()}-{uuid.getnode():x}"
            # Shared memory is namespaced per user on some systems;
            # same uid is also the permission boundary for shm_open.
            _HOST_ID = f"{boot_id}:{os.getuid()}"
        return _HOST_ID


def map_enabled() -> bool:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return bool(GLOBAL_CONFIG.same_host_plane)


def map_min_bytes() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return int(GLOBAL_CONFIG.same_host_map_min_kb) * 1024


def pin_ttl_s() -> float:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return float(GLOBAL_CONFIG.same_host_pin_ttl_s)


class LeaseTable:
    """Owner-side pin registry for mapped-out objects.

    ``grant`` pins (via ``on_release``'s dual: the caller pins before
    granting and hands the unpin closure here); ``release`` unpins.
    ``sweep`` releases leases that are BOTH older than the TTL and held
    by an unreachable holder — liveness-gated expiry, so a healthy
    puller holding a mapping for a long time keeps its lease, while a
    SIGKILLed one cannot pin the owner's memory past one TTL + sweep
    period."""

    def __init__(self):
        self._lock = lock_witness.Lock("same_host.LeaseTable")
        self._next = 0
        # token -> (id_bytes, holder_addr, granted_monotonic, on_release)
        self._leases: dict[str, tuple] = {}
        self.granted = 0
        self.released = 0
        self.expired = 0

    def grant(self, id_bytes: bytes, holder: str,
              on_release: Callable[[], None] | None = None) -> str:
        with self._lock:
            self._next += 1
            token = f"{self._next}-{os.urandom(4).hex()}"
            self._leases[token] = (id_bytes, holder, time.monotonic(),
                                   on_release)
            self.granted += 1
        return token

    def release(self, token: str) -> bool:
        with self._lock:
            lease = self._leases.pop(token, None)
            if lease is not None:
                self.released += 1
        if lease is None:
            return False
        self._run_release(lease)
        return True

    def release_object(self, id_bytes: bytes) -> int:
        """Owner freed the object: drop every lease on it (the
        underlying unpin makes the final delete effective)."""
        with self._lock:
            victims = [t for t, l in self._leases.items()
                       if l[0] == id_bytes]
            leases = [self._leases.pop(t) for t in victims]
            self.released += len(leases)
        for lease in leases:
            self._run_release(lease)
        return len(leases)

    def pinned_ids(self) -> set[bytes]:
        with self._lock:
            return {l[0] for l in self._leases.values()}

    def sweep(self, ttl_s: float,
              probe: Callable[[str], bool] | None = None) -> int:
        """Release leases older than ``ttl_s`` whose holder is
        unreachable (``probe`` returns False). With no probe, age alone
        expires — callers that cannot ping (unit tests) get plain TTL
        semantics."""
        from ray_tpu._private import chaos

        now = time.monotonic()
        forced: set[str] = set()
        with self._lock:
            stale = [(t, l) for t, l in self._leases.items()
                     if now - l[2] > ttl_s]
            if chaos.ACTIVE is not None:
                # Chaos: expire a lease early, bypassing the liveness
                # probe — pullers must survive their mapping's pin
                # vanishing under them (the owner-crash shape without
                # the crash).
                for t, l in self._leases.items():
                    if (t, l) not in stale \
                            and chaos.ACTIVE.should("lease.expire"):
                        stale.append((t, l))
                        forced.add(t)
        expired = []
        alive_holders: dict[str, bool] = {}
        for token, lease in stale:
            holder = lease[1]
            if probe is not None and token not in forced:
                if holder not in alive_holders:
                    try:
                        alive_holders[holder] = bool(probe(holder))
                    except Exception:  # noqa: BLE001 — unreachable
                        alive_holders[holder] = False
                if alive_holders[holder]:
                    # Holder lives: refresh the lease instead of
                    # re-probing it every sweep pass.
                    with self._lock:
                        if token in self._leases:
                            i, h, _, cb = self._leases[token]
                            self._leases[token] = (i, h, now, cb)
                    continue
            with self._lock:
                lease = self._leases.pop(token, None)
                if lease is not None:
                    self.expired += 1
            if lease is not None:
                expired.append(lease)
        for lease in expired:
            self._run_release(lease)
        return len(expired)

    def clear(self) -> None:
        with self._lock:
            leases = list(self._leases.values())
            self._leases.clear()
        for lease in leases:
            self._run_release(lease)

    @staticmethod
    def _run_release(lease: tuple) -> None:
        cb = lease[3]
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — release is best-effort
                pass

    def stats(self) -> dict:
        with self._lock:
            return {"active": len(self._leases), "granted": self.granted,
                    "released": self.released, "expired": self.expired}


def pid_is_dead(pid: int) -> bool:
    """0-signal liveness probe shared by the orphan sweepers (native
    arena segments here, per-pid spill directories in
    spill_manager.sweep_orphan_spill_dirs): True ONLY for a pid that
    provably does not exist — alive-under-another-user (EPERM) counts
    as alive, so cross-user state is never touched."""
    try:
        os.kill(pid, 0)
        return False
    except ProcessLookupError:
        return True
    except PermissionError:
        return False


def sweep_orphan_shm() -> int:
    """Unlink native arena segments (``/dev/shm/ray_tpu_arena_<pid>``)
    whose owning process died without cleaning up.

    The native arena is created by shm_open (plasma_store.cpp), so a
    SIGKILLed daemon's segment has NO surviving unlinker — unlike
    Python ``SharedMemory`` segments, which the multiprocessing
    resource tracker reclaims. Any co-hosted survivor (daemon sweep
    loops, the driver's pin sweeper) reaps them: the name carries the
    owner pid, so liveness is one 0-signal probe, and only same-uid
    segments are touched. Existing mappings of an unlinked segment
    stay valid (POSIX); only new attaches — already doomed, the owner
    is dead — fail."""
    import re

    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return 0
    swept = 0
    for name in names:
        match = re.fullmatch(r"ray_tpu_arena_(\d+)", name)
        if not match:
            continue
        pid = int(match.group(1))
        if pid == os.getpid() or not pid_is_dead(pid):
            continue
        path = os.path.join("/dev/shm", name)
        try:
            if os.stat(path).st_uid != os.getuid():
                continue
            os.unlink(path)
            swept += 1
        except OSError:
            continue  # raced another sweeper / permissions
    return swept


def attach_segment(name: str):
    """Open a peer-owned segment by name for mapping. On Python 3.12+
    (which registers attaches with the resource tracker) the attach is
    untracked so THIS process's exit can never unlink the owner's
    segment; earlier Pythons don't register attaches, and untracking
    would instead unregister the owner's entry when both sides share a
    tracker (in-process tests). Raises OSError when the name is gone."""
    import sys
    from multiprocessing import shared_memory

    from ray_tpu._private.shm_store import untrack

    seg = shared_memory.SharedMemory(name=name)
    if sys.version_info >= (3, 12):
        untrack(seg)
    return seg


def fetch_mapped_blob(call, id_bytes: bytes, my_addr: str,
                      my_host: str):
    """One-shot same-host fetch for consumers without a mapping cache
    (the driver materializing a RemoteBlob): ask the holder for a plan,
    and when it grants a map lease, copy the framed bytes straight out
    of its shared memory — one memcpy, no chunked RPC. Returns the
    bytes or None (caller falls back to the chunked pull). The lease is
    released either way."""
    try:
        plan = call("fetch_plan", id_bytes, my_addr, my_host)
    except Exception:  # noqa: BLE001 — holder gone / pre-plan peer
        return None
    info = plan[2] if plan is not None and len(plan) > 2 else None
    if not info or info.get("host") != my_host \
            or not info.get("token"):
        return None
    token = info["token"]
    try:
        size = int(info.get("size", 0))
        if info.get("kind") == "seg":
            try:
                seg = attach_segment(info["name"])
            except (OSError, ValueError):
                return None
            try:
                return bytes(seg.buf[:size])
            finally:
                try:
                    seg.close()
                except (BufferError, OSError):
                    pass  # borrowed map: owner/tracker reclaims
        if info.get("kind") == "arena":
            from ray_tpu._private.arena_store import ArenaStore

            arena = ArenaStore.attach(info["name"])
            if arena is None:
                return None
            try:
                peek = arena.peek(info["key"])
                if peek is None:
                    return None
                offset, obj_size = peek
                return bytes(arena.view_at(offset, obj_size))
            finally:
                arena.close()
        return None
    except Exception:  # noqa: BLE001 — any failure: chunked fallback
        return None
    finally:
        try:
            call("unpin_object", token)
        except Exception:  # noqa: BLE001 — TTL sweep is the backstop
            pass


class PeerArenaRegistry:
    """Cached attachments to peer-owned arenas, by shm name.

    Attachments are kept for the process's life (an mmap is cheap to
    hold, expensive to churn); ``close_all`` detaches on shutdown. The
    mapping is used READ-ONLY by convention — the puller never takes
    in-arena references (the owner pins on its behalf via the lease),
    so a crashed puller cannot corrupt or wedge the owner's arena."""

    def __init__(self):
        self._lock = lock_witness.Lock("same_host.PeerArenaRegistry")
        self._arenas: dict[str, object] = {}

    def get(self, name: str):
        from ray_tpu._private.arena_store import ArenaStore

        with self._lock:
            arena = self._arenas.get(name)
            if arena is None and name not in self._arenas:
                arena = ArenaStore.attach(name)
                if arena is not None:
                    self._arenas[name] = arena
            return arena

    def view(self, name: str, key: bytes):
        """Zero-copy memoryview of a sealed object in a peer arena, or
        None (arena gone / object evicted). Valid only while the
        owner-side lease pins the object."""
        arena = self.get(name)
        if arena is None:
            return None
        peek = arena.peek(key)
        if peek is None:
            return None
        offset, size = peek
        return arena.view_at(offset, size)

    def close_all(self) -> None:
        with self._lock:
            arenas = [a for a in self._arenas.values() if a is not None]
            self._arenas.clear()
        for arena in arenas:
            try:
                arena.close()
            except Exception:  # noqa: BLE001 — detach is best-effort
                pass
