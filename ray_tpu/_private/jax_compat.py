"""Version-portability shims for the handful of jax APIs that moved
between jax 0.4.x and 0.5+.

The model/parallelism code targets the modern ambient-mesh world
(``jax.set_mesh`` + ``jax.shard_map`` + abstract meshes). Older jax
(< 0.5) spells these ``jax.experimental.shard_map.shard_map`` (with
``check_rep`` instead of ``check_vma``) and has no ambient abstract
mesh — only the ``with mesh:`` physical-mesh context. These wrappers
pick whichever spelling the installed jax provides so importing the
library never raises AttributeError on an older jax; call sites that
genuinely need ``jax.set_mesh`` semantics should gate on
:data:`HAS_SET_MESH` (tests skip via the same flag).
"""

from __future__ import annotations

import jax

#: True when this jax has the ambient-mesh API (jax.set_mesh /
#: jax.sharding.get_abstract_mesh). Tests that drive models under
#: ``with jax.set_mesh(...)`` skip when False.
HAS_SET_MESH = hasattr(jax, "set_mesh")


def ambient_mesh():
    """The ambient mesh, or None when none is set (or unknowable).

    New jax: ``jax.sharding.get_abstract_mesh()`` (empty mesh → None).
    Old jax: the ``with mesh:`` physical-mesh context, which is what
    pjit-era code used as its ambient mesh.
    """
    getter = getattr(jax.sharding, "get_abstract_mesh", None)
    if getter is not None:
        mesh = getter()
        return None if mesh.empty else mesh
    try:
        from jax.interpreters import pxla

        mesh = pxla.thread_resources.env.physical_mesh
        return None if mesh.empty else mesh
    except Exception:  # noqa: BLE001 — no context machinery at all
        return None


def shard_map(f, *, mesh=None, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` (new) or the jax.experimental spelling (old).

    ``mesh=None`` means "use the ambient mesh": passed through on new
    jax, resolved via :func:`ambient_mesh` for the legacy API (which
    requires an explicit mesh). ``check_vma`` maps to the legacy
    ``check_rep``.
    """
    new = getattr(jax, "shard_map", None)
    if new is not None:
        kwargs = {"in_specs": in_specs, "out_specs": out_specs}
        if mesh is not None:
            kwargs["mesh"] = mesh
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return new(f, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy

    if mesh is None:
        mesh = ambient_mesh()
        if mesh is None:
            raise RuntimeError(
                "shard_map needs a mesh: this jax has no ambient-mesh "
                "API (jax.set_mesh) — pass mesh= explicitly, enter a "
                "`with mesh:` context, or upgrade jax")
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return legacy(f, **kwargs)
