"""Version-portability shims for the handful of jax APIs that moved
between jax 0.4.x and 0.5+.

The model/parallelism code targets the modern ambient-mesh world
(``jax.set_mesh`` + ``jax.shard_map`` + abstract meshes). Older jax
(< 0.5) spells these ``jax.experimental.shard_map.shard_map`` (with
``check_rep`` instead of ``check_vma``) and has no ambient abstract
mesh — only the ``with mesh:`` physical-mesh context. These wrappers
pick whichever spelling the installed jax provides so importing the
library never raises AttributeError on an older jax; call sites that
genuinely need ``jax.set_mesh`` semantics should gate on
:data:`HAS_SET_MESH` (tests skip via the same flag).
"""

from __future__ import annotations

import jax

#: True when this jax has the ambient-mesh API (jax.set_mesh /
#: jax.sharding.get_abstract_mesh). Tests that drive models under
#: ``with jax.set_mesh(...)`` skip when False.
HAS_SET_MESH = hasattr(jax, "set_mesh")

_CPU_MULTIPROCESS: "bool | None" = None

_CPU_MULTIPROCESS_PROBE = r"""
import os, sys
rank = int(sys.argv[1])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.distributed.initialize(sys.argv[2], num_processes=2,
                           process_id=rank)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()), ("dp",))
arr = jax.make_array_from_callback(
    (4,), NamedSharding(mesh, P("dp")),
    lambda idx: np.ones((1,), np.float32))
assert float(jax.jit(jnp.sum)(arr)) == 4.0
"""


def has_cpu_multiprocess(timeout_s: float = 120.0) -> bool:
    """Whether this jax/jaxlib can EXECUTE computations over a device
    mesh spanning multiple CPU-backend processes.

    Older jaxlib builds form the jax.distributed world fine but die at
    execute time with "Multiprocess computations aren't implemented on
    the CPU backend" (even with gloo collectives requested), so no
    version/attribute sniff is trustworthy — the probe runs a minimal
    2-process 1-collective program once and memoizes the verdict.
    Tests that gang CPU processes into one mesh skip when False.
    ``RAY_TPU_ASSUME_CPU_MULTIPROCESS=0/1`` overrides (CI determinism,
    or boxes where the probe itself is unwanted)."""
    global _CPU_MULTIPROCESS
    if _CPU_MULTIPROCESS is not None:
        return _CPU_MULTIPROCESS
    import os

    override = os.environ.get("RAY_TPU_ASSUME_CPU_MULTIPROCESS")
    if override is not None:
        _CPU_MULTIPROCESS = override.strip().lower() in (
            "1", "true", "yes", "on")
        return _CPU_MULTIPROCESS
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CPU_MULTIPROCESS_PROBE, str(rank),
         coord], env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL) for rank in range(2)]
    ok = True
    try:
        for p in procs:
            if p.wait(timeout=timeout_s) != 0:
                ok = False
    except subprocess.TimeoutExpired:
        ok = False
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    _CPU_MULTIPROCESS = ok
    return ok


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient mesh for the
    jitted calls inside — the TP path the LLM engine / llm.py docs
    reference. ``mesh=None`` is a no-op (single-chip).

    New jax: ``jax.set_mesh(mesh)`` (a context manager since 0.7; on
    the in-between releases where it sets globally we fall back to the
    ``jax.sharding.use_mesh`` spelling). Old jax (< 0.5, no ambient
    API): the ``with mesh:`` physical-mesh context, which is exactly
    what pjit-era code used — so engine code written against
    ``jax_compat.set_mesh`` imports AND runs clean on jax 0.4.x.
    """
    import contextlib

    if mesh is None:
        return contextlib.nullcontext()
    new = getattr(jax, "set_mesh", None)
    if new is not None:
        ctx = new(mesh)
        if hasattr(ctx, "__enter__"):
            return ctx
        use_mesh = getattr(jax.sharding, "use_mesh", None)
        if use_mesh is not None:
            return use_mesh(mesh)
        return contextlib.nullcontext()  # already installed globally

    @contextlib.contextmanager
    def _physical(mesh):
        with mesh:
            yield mesh

    return _physical(mesh)


def ambient_mesh():
    """The ambient mesh, or None when none is set (or unknowable).

    New jax: ``jax.sharding.get_abstract_mesh()`` (empty mesh → None).
    Old jax: the ``with mesh:`` physical-mesh context, which is what
    pjit-era code used as its ambient mesh.
    """
    getter = getattr(jax.sharding, "get_abstract_mesh", None)
    if getter is not None:
        mesh = getter()
        return None if mesh.empty else mesh
    try:
        from jax.interpreters import pxla

        mesh = pxla.thread_resources.env.physical_mesh
        return None if mesh.empty else mesh
    except Exception:  # noqa: BLE001 — no context machinery at all
        return None


def shard_map(f, *, mesh=None, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` (new) or the jax.experimental spelling (old).

    ``mesh=None`` means "use the ambient mesh": passed through on new
    jax, resolved via :func:`ambient_mesh` for the legacy API (which
    requires an explicit mesh). ``check_vma`` maps to the legacy
    ``check_rep``.
    """
    new = getattr(jax, "shard_map", None)
    if new is not None:
        kwargs = {"in_specs": in_specs, "out_specs": out_specs}
        if mesh is not None:
            kwargs["mesh"] = mesh
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return new(f, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy

    if mesh is None:
        mesh = ambient_mesh()
        if mesh is None:
            raise RuntimeError(
                "shard_map needs a mesh: this jax has no ambient-mesh "
                "API (jax.set_mesh) — pass mesh= explicitly, enter a "
                "`with mesh:` context, or upgrade jax")
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return legacy(f, **kwargs)
