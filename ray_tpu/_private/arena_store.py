"""ArenaStore — Python wrapper over the native shared-memory arena.

Reference: src/ray/object_manager/plasma/client.h (PlasmaClient:
Create/Seal/Get/Release/Delete against the store's shared arena). The
native side (ray_tpu/_native/plasma_store.cpp) keeps the allocator,
object table, and robust lock in shared memory; this wrapper adds the
Python-facing buffer protocol.

Ownership model: the driver process creates the arena; pool workers
attach by name (RAY_TPU_ARENA_NAME in their environment). Objects are
keyed by 16-byte ids (ObjectID.binary() or os.urandom for transport
blobs).
"""

from __future__ import annotations

import ctypes
import os
from typing import Any

from ray_tpu._native import load as _load_native


class ArenaFullError(Exception):
    """Arena could not satisfy the allocation even after eviction."""


class ArenaStore:
    """One mapped shared-memory arena (create or attach)."""

    def __init__(self, handle, name: str, owner: bool):
        self._lib = _load_native()
        self._handle = handle
        self.name = name
        self.owner = owner
        self._base = self._lib.rt_store_base(handle)

    # -- lifecycle ----------------------------------------------------
    @classmethod
    def create(cls, name: str, capacity_bytes: int,
               table_capacity: int = 4096) -> "ArenaStore | None":
        lib = _load_native()
        if lib is None:
            return None
        handle = lib.rt_store_create(
            name.encode(), capacity_bytes, table_capacity)
        if not handle:
            return None
        return cls(handle, name, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ArenaStore | None":
        lib = _load_native()
        if lib is None:
            return None
        handle = lib.rt_store_attach(name.encode())
        if not handle:
            return None
        return cls(handle, name, owner=False)

    def close(self) -> None:
        if self._handle is None:
            return
        if self.owner:
            self._lib.rt_store_destroy(self._handle, self.name.encode())
        else:
            self._lib.rt_store_detach(self._handle)
        self._handle = None

    # -- objects ------------------------------------------------------
    def _view(self, offset: int, size: int) -> memoryview:
        addr = ctypes.addressof(self._base.contents) + offset
        return memoryview(
            (ctypes.c_uint8 * size).from_address(addr)).cast("B")

    def put_bytes(self, object_id: bytes, payloads) -> bool:
        """Write ``payloads`` (an iterable of buffers) as one object.

        Returns False when the arena cannot hold it (caller falls back
        to a dedicated segment).
        """
        total = sum(len(p) for p in payloads)
        offset = self._lib.rt_store_create_object(
            self._handle, object_id, total)
        if not offset:
            return False
        view = self._view(offset, total)
        pos = 0
        for p in payloads:
            n = len(p)
            view[pos:pos + n] = bytes(p) if not isinstance(
                p, (bytes, bytearray, memoryview)) else p
            pos += n
        self._lib.rt_store_seal(self._handle, object_id)
        return True

    def create_for_write(self, object_id: bytes,
                         size: int) -> memoryview | None:
        """Allocate an unsealed object and return a writable view into
        the arena (plasma's Create). Caller writes then ``seal``s.
        Returns None when the arena cannot hold it."""
        offset = self._lib.rt_store_create_object(
            self._handle, object_id, size)
        if not offset:
            return None
        return self._view(offset, size)

    def seal(self, object_id: bytes) -> None:
        self._lib.rt_store_seal(self._handle, object_id)

    def seal_pinned(self, object_id: bytes) -> None:
        """Seal + take a reference atomically: the object is never in
        the evictable (sealed, refcount-0) state, so it survives until
        ``unpin`` even under arena pressure. Used for ownership handoff
        (worker result -> driver directory)."""
        self._lib.rt_store_seal_pinned(self._handle, object_id)

    def unpin(self, object_id: bytes) -> None:
        """Drop a reference taken by seal_pinned (or pin)."""
        self._lib.rt_store_release(self._handle, object_id)

    def pin(self, object_id: bytes) -> int | None:
        """Take a reference on a sealed object WITHOUT reading it
        (plasma's Get, minus the buffer). Returns the payload size, or
        None when absent/unsealed. The object cannot be evicted (and a
        delete is deferred) until the matching ``unpin`` — the owner
        uses this to pin objects on behalf of same-host peers that map
        this arena (see same_host.LeaseTable)."""
        size = ctypes.c_uint64()
        offset = self._lib.rt_store_get(
            self._handle, object_id, ctypes.byref(size))
        if not offset:
            return None
        return size.value

    def peek(self, object_id: bytes) -> tuple[int, int] | None:
        """(offset, size) of a sealed object WITHOUT touching its
        refcount — the read-only path for peers attached to someone
        else's arena (the owner's lease pin keeps the offset valid;
        sealed objects never move, eviction only frees)."""
        size = ctypes.c_uint64()
        offset = self._lib.rt_store_peek(
            self._handle, object_id, ctypes.byref(size))
        if not offset:
            return None
        return offset, size.value

    def view_at(self, offset: int, size: int) -> memoryview:
        """Public zero-copy view of an arena range (callers pair it
        with ``peek`` under an active pin/lease)."""
        return self._view(offset, size)

    def get_bytes(self, object_id: bytes) -> bytes | None:
        """Copy an object's payload out of the arena.

        Copies deliberately: a zero-copy view could be invalidated by
        eviction/reuse after release. Large objects (where zero-copy
        matters) use dedicated segments, not the arena — see
        shm_store.py's size policy.
        """
        size = ctypes.c_uint64()
        offset = self._lib.rt_store_get(
            self._handle, object_id, ctypes.byref(size))
        if not offset:
            return None
        try:
            return bytes(self._view(offset, size.value))
        finally:
            self._lib.rt_store_release(self._handle, object_id)

    def delete(self, object_id: bytes) -> None:
        self._lib.rt_store_delete(self._handle, object_id)

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.rt_store_contains(self._handle, object_id))

    def stats(self) -> dict:
        u = [ctypes.c_uint64() for _ in range(5)]
        self._lib.rt_store_stats(self._handle, *[ctypes.byref(x) for x in u])
        return {
            "used_bytes": u[0].value,
            "capacity_bytes": u[1].value,
            "num_objects": u[2].value,
            "num_evictions": u[3].value,
            "alloc_failures": u[4].value,
        }


def default_arena_name() -> str:
    return f"/ray_tpu_arena_{os.getpid()}"
