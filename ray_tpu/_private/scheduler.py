"""Cluster resource scheduling and task dispatch.

TPU-native analogue of the reference's two-level scheduler:

- ``ClusterState`` mirrors ClusterResourceManager + ClusterResourceScheduler
  (reference: src/ray/raylet/scheduling/cluster_resource_scheduler.h:44):
  a view of every node's total/available resources plus policy-based node
  selection (hybrid pack-then-spread, spread, node-affinity — reference:
  src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.cc).
- ``NodeExecutor`` mirrors the raylet's LocalTaskManager + WorkerPool
  (reference: src/ray/raylet/local_task_manager.h:58, worker_pool.h):
  per-node dispatch queue with resource admission; a Python thread plays
  the role of a leased worker (true multiprocess workers are layered on in
  ray_tpu/_private/worker_pool.py).

Nodes are in-process "virtual nodes" so multi-node scheduling logic is
fully exercised on one machine — the same strategy as the reference's
cluster_utils.Cluster test fixture (python/ray/cluster_utils.py:108).

Deadlock note: a task blocked in ``get()`` releases its CPU admission and
reacquires on wake (reference behavior: workers blocked in ray.get return
their CPU to the raylet), so nested task graphs cannot starve.
"""

from __future__ import annotations

import heapq
import itertools
import threading

from ray_tpu._private import lock_witness
import traceback
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu._private import perf_plane as perf
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import NodeID
from ray_tpu._private.task import TaskSpec
from ray_tpu.util import tracing

_DISPATCH_ORDER = itertools.count(1).__next__

# Locality- and load-aware placement (the observability loop closed:
# pick_node consumes the object directory's byte-weighted argument
# locality and the heartbeat-shipped node-stats feed). The ONE
# production branch per site — disarmed, pick_node is byte-identical
# to the classic hybrid policy (chaos.ACTIVE / perf.PERF_ON
# discipline). Armed from the locality_aware_scheduling knob at
# Runtime init; daemons inherit RAY_TPU_LOCALITY_AWARE_SCHEDULING
# through the child env at import.
LOCALITY_ON: bool = True

# Load-score margin (in queued-task units) the live feed must show
# before the scorer overrides the classic utilization ordering: small
# deltas keep the disarmed placement (and its packing behavior); only
# a genuinely skewed backlog spills.
_SPILL_MARGIN = 2.0


def init_sched_from_config() -> None:
    """Arm/disarm locality-aware placement from config (Runtime init
    and daemon boot both call this)."""
    global LOCALITY_ON
    LOCALITY_ON = bool(GLOBAL_CONFIG.locality_aware_scheduling)


try:
    init_sched_from_config()
except Exception:  # noqa: BLE001 — config unavailable mid-bootstrap
    pass

# How long a node's self-reported availability stays authoritative.
# Push deltas only fire on change, so a lost delta would otherwise pin
# a stale low-water mark forever; past the TTL admission falls back to
# the driver's own lease ledger (the pre-syncer behavior, where busy
# remote nodes are discovered via spillback rejections). Must exceed
# the driver's 10s list_nodes safety-net resync (which refreshes
# reported_at from the node table): in steady state — another tenant
# holding a node at CONSTANT load publishes no deltas — the resync
# re-arms the report before it expires, so multi-tenant admission
# protection never lapses with a live head.
REPORTED_AVAILABILITY_TTL_S = 12.0


@dataclass
class NodeState:
    """One node's resource ledger.

    ``available`` is this driver's lease ledger (debited/credited as it
    dispatches). ``reported`` is the node's own last-pushed ground
    truth (syncer channel) — it also reflects OTHER drivers' load.
    Admission takes the min of both per key: the ledger is instantly
    correct for our in-flight work, the report is authoritative for
    everyone else's, and min() is conservative under the races between
    them (reference: the raylet's local view vs the syncer'd global
    view, cluster_resource_scheduler.h:44)."""

    node_id: NodeID
    total: dict[str, float]
    available: dict[str, float]
    labels: dict[str, str] = field(default_factory=dict)
    alive: bool = True
    reported: dict[str, float] | None = None
    reported_at: float = 0.0
    # This driver's outstanding leases, plus a snapshot of them taken
    # when ``reported`` last arrived: the report is compensated by OUR
    # lease delta since it was measured. Without this, a node whose
    # report says "0 CPUs free" stays unschedulable for a full
    # poke-coalesce + pubsub round trip AFTER our own task released its
    # lease — capping slot turnover (and hence cluster-wide task
    # throughput) at the sync latency instead of the task duration.
    inflight: dict[str, float] = field(default_factory=dict)
    reported_inflight: dict[str, float] = field(default_factory=dict)
    # Live load view from the node's heartbeat-shipped stats feed (the
    # GCS node-stats table, synced by the driver's watcher):
    # admitted-reservation depth, running tasks and the recent
    # admit_worker/exec p50s, with a receipt stamp so stale entries
    # decay out of the score (update_node_stats). 0.0 stats_at = never
    # reported.
    stats_at: float = 0.0
    stats_running: float = 0.0
    stats_depth: float = 0.0
    stats_wait_s: float = 0.0

    def effective_available(self, key: str) -> float:
        avail = self.available.get(key, 0.0)
        if (self.reported is None
                or time.monotonic() - self.reported_at
                > REPORTED_AVAILABILITY_TTL_S):
            return avail
        if key not in self.reported:
            return avail
        rep = self.reported[key] + (
            self.reported_inflight.get(key, 0.0)
            - self.inflight.get(key, 0.0))
        return min(avail, rep)

    def fits(self, demand: dict[str, float]) -> bool:
        return all(self.effective_available(k) + 1e-9 >= v
                   for k, v in demand.items())

    def feasible(self, demand: dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())

    def acquire(self, demand: dict[str, float]) -> None:
        for key, value in demand.items():
            self.available[key] = self.available.get(key, 0.0) - value
            self.inflight[key] = self.inflight.get(key, 0.0) + value

    def release(self, demand: dict[str, float]) -> None:
        for key, value in demand.items():
            self.available[key] = self.available.get(key, 0.0) + value
            self.inflight[key] = self.inflight.get(key, 0.0) - value

    def utilization(self) -> float:
        best = 0.0
        for key, total in self.total.items():
            if total > 0:
                used = total - self.available.get(key, 0.0)
                best = max(best, used / total)
        return best


class ClusterState:
    """Cluster-wide resource view + node selection policies."""

    def __init__(self, spread_threshold: float = 0.5):
        self._lock = lock_witness.Condition(
            "scheduler.ClusterState", plain_lock=True)
        self._nodes: dict[NodeID, NodeState] = {}
        self._spread_threshold = spread_threshold
        self._rr_counter = 0
        # Placement-decision counters (mutated under self._lock in
        # pick_node, surfaced via execution_pipeline_stats()["sched"]
        # and the ray_tpu_sched_decisions_total /metrics family).
        self.sched = {
            "locality_hits": 0,
            "locality_bytes_saved": 0,
            "load_spillbacks": 0,
            "stale_stats_skips": 0,
        }

    # ----------------------------------------------------------- membership

    def add_node(self, node: NodeState) -> None:
        with self._lock:
            self._nodes[node.node_id] = node
            self._lock.notify_all()

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                node.alive = False

    def revive_node(self, node_id: NodeID) -> bool:
        """Bring a transiently-removed node back WITHOUT resetting its
        ledger: in-flight tasks still hold acquired resources, and a
        fresh NodeState would let their releases oversubscribe the node.
        Returns False when the node was never known (add it instead)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return False
            node.alive = True
            self._lock.notify_all()
            return True

    def nodes(self) -> list[NodeState]:
        with self._lock:
            return [n for n in self._nodes.values() if n.alive]

    def get_node(self, node_id: NodeID) -> NodeState | None:
        with self._lock:
            return self._nodes.get(node_id)

    def total_resources(self) -> dict[str, float]:
        with self._lock:
            out: dict[str, float] = {}
            for node in self._nodes.values():
                if not node.alive:
                    continue
                for k, v in node.total.items():
                    out[k] = out.get(k, 0.0) + v
            return out

    def available_resources(self) -> dict[str, float]:
        with self._lock:
            out: dict[str, float] = {}
            for node in self._nodes.values():
                if not node.alive:
                    continue
                for k, v in node.available.items():
                    out[k] = out.get(k, 0.0) + v
            return out

    # ------------------------------------------------------------ selection

    def pick_node(self, demand: dict[str, float], strategy,
                  exclude: set[NodeID] | None = None,
                  locality: dict | None = None) -> NodeState | None:
        """Select a feasible node per policy; None if nothing fits *now*.

        Hybrid policy (reference: hybrid_scheduling_policy.cc): prefer
        packing onto low-index nodes until utilization crosses the spread
        threshold, then prefer the least-utilized node.

        ``locality`` ({node hex -> resident bytes of the task's large
        args}) and the heartbeat-shipped node-stats feed refine the
        choice while LOCALITY_ON (see _pick_scored); disarmed, the
        classic ordering above is byte-identical.
        """
        with self._lock:
            candidates = [
                n for n in self._nodes.values()
                if n.alive and (exclude is None or n.node_id not in exclude)
            ]
            if strategy is not None and strategy.kind == "NODE_AFFINITY":
                target = [n for n in candidates if n.node_id.hex() == strategy.node_id]
                if target and target[0].fits(demand):
                    return target[0]
                # Soft affinity falls back to the default policy when the
                # preferred node is gone/full (reference:
                # scheduling_strategies.py NodeAffinitySchedulingStrategy
                # soft=True); hard affinity cannot schedule elsewhere.
                if not getattr(strategy, "soft", False):
                    return None
            fitting = [n for n in candidates if n.fits(demand)]
            if not fitting:
                return None
            if strategy is not None and strategy.kind == "SPREAD":
                # Round-robin across fitting nodes (reference: spread policy).
                self._rr_counter += 1
                return fitting[self._rr_counter % len(fitting)]
            if LOCALITY_ON:
                chosen = self._pick_scored(fitting, locality)
                if chosen is not None:
                    return chosen
            under = [n for n in fitting if n.utilization() < self._spread_threshold]
            pool = under if under else fitting
            return min(pool, key=lambda n: (n.utilization(), n.node_id.hex()))

    def _pick_scored(self, fitting: "list[NodeState]",
                     locality: dict | None) -> NodeState | None:
        """Locality- and load-aware refinement of the hybrid pick.
        Caller holds self._lock. Returns the chosen node (counting the
        decision) or None to fall back to the classic ordering.

        Scoring (documented in README "Scheduling"):
        1. Byte-weighted locality wins outright: among fitting nodes,
           the one(s) holding the most large-arg bytes; ties broken by
           load, then the classic (utilization, hex) ordering.
        2. Otherwise the classic pack-then-spread pool is re-ranked by
           the live load score ``running + depth + p50 wait`` from the
           node-stats feed — but only when the feed shows a real skew
           (>= _SPILL_MARGIN) or the classic choice's stats are STALE
           (a wedged daemon that stopped heartbeating must not keep
           attracting work by looking idle).
        """
        now = time.monotonic()
        try:
            stale_s = float(GLOBAL_CONFIG.sched_stats_stale_s)
        except Exception:  # noqa: BLE001 — config gone mid-teardown
            stale_s = 6.0

        def load(n: NodeState) -> "float | None":
            """Queue-pressure score from the node's last stats push;
            None = never reported or decayed out (stale)."""
            if n.stats_at <= 0.0 or now - n.stats_at > stale_s:
                return None
            return n.stats_running + n.stats_depth + n.stats_wait_s

        if locality:
            best = 0.0
            best_nodes: list[NodeState] = []
            for n in fitting:
                b = float(locality.get(n.node_id.hex(), 0.0))
                if b > best:
                    best, best_nodes = b, [n]
                elif b == best and best > 0.0:
                    best_nodes.append(n)
            if best > 0.0:
                chosen = min(best_nodes, key=lambda n: (
                    load(n) if load(n) is not None else float("inf"),
                    n.utilization(), n.node_id.hex()))
                self.sched["locality_hits"] += 1
                self.sched["locality_bytes_saved"] += int(best)
                return chosen
        under = [n for n in fitting
                 if n.utilization() < self._spread_threshold]
        pool = under if under else fitting
        loads = {id(n): load(n) for n in pool}
        if all(v is None for v in loads.values()):
            return None  # no live feed at all: classic ordering
        default = min(pool, key=lambda n: (n.utilization(),
                                           n.node_id.hex()))
        chosen = min(pool, key=lambda n: (
            loads[id(n)] if loads[id(n)] is not None else float("inf"),
            n.utilization(), n.node_id.hex()))
        if chosen is default:
            return default
        default_load = loads[id(default)]
        chosen_load = loads[id(chosen)]
        if default_load is None:
            # The classic choice's stats went stale (daemon wedged or
            # silent): spill to a node with a FRESH idle report.
            self.sched["stale_stats_skips"] += 1
            if tracing.TRACE_ON:
                tracing.instant("sched:stale_stats_skip", {
                    "skipped": default.node_id.hex()[:16],
                    "chosen": chosen.node_id.hex()[:16]})
            return chosen
        if chosen_load is not None \
                and default_load - chosen_load >= _SPILL_MARGIN:
            # Skewed backlog: the classic choice is measurably more
            # loaded than a fresh-stats idle node — spill.
            self.sched["load_spillbacks"] += 1
            if tracing.TRACE_ON:
                tracing.instant("sched:load_spillback", {
                    "from": default.node_id.hex()[:16],
                    "to": chosen.node_id.hex()[:16],
                    "load_delta": round(default_load - chosen_load, 3)})
            return chosen
        return default

    def update_node_stats(self, node_id: NodeID, running: float,
                          depth: float, wait_s: float,
                          age_s: float = 0.0) -> None:
        """Fold one node's heartbeat-shipped stats snapshot into the
        load view. ``age_s`` is the GCS-side receipt age at fetch time,
        so staleness keeps decaying between driver syncs."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return
            node.stats_running = float(running)
            node.stats_depth = float(depth)
            node.stats_wait_s = float(wait_s)
            node.stats_at = time.monotonic() - max(0.0, float(age_s))

    def record_locality_hit(self, bytes_saved: float) -> None:
        """A placement kept a task next to its bytes outside the full
        scored scan (the sticky fast path re-confirming the max-bytes
        holder): count it like a scan hit."""
        with self._lock:
            self.sched["locality_hits"] += 1
            self.sched["locality_bytes_saved"] += int(bytes_saved)

    def sched_counters(self) -> dict:
        with self._lock:
            return dict(self.sched)

    def is_feasible(self, demand: dict[str, float]) -> bool:
        with self._lock:
            return any(n.feasible(demand) for n in self._nodes.values() if n.alive)

    # ------------------------------------------------------- acquire/release

    def try_acquire(self, node_id: NodeID, demand: dict[str, float]) -> bool:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive or not node.fits(demand):
                return False
            node.acquire(demand)
            return True

    def release(self, node_id: NodeID, demand: dict[str, float]) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                node.release(demand)
            self._lock.notify_all()

    def release_many(self, node_id: NodeID, demands: list) -> None:
        """One lock pass + one wakeup for a completion group's worth of
        releases (the per-task release was two lock acquires per task
        on the batch completion hot path)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                for demand in demands:
                    node.release(demand)
            self._lock.notify_all()

    def update_reported(self, node_id: NodeID,
                        available: dict[str, float]) -> None:
        """Syncer push: the node's own availability report arrived
        (includes other drivers' load). Wakes the dispatcher — freed
        remote capacity is a scheduling opportunity."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                node.reported = dict(available)
                node.reported_at = time.monotonic()
                # The report reflects our leases AS OF NOW; future
                # effective_available compensates only for our delta
                # past this snapshot.
                node.reported_inflight = dict(node.inflight)
                self._lock.notify_all()

    def acquire_batch(self, demand: dict[str, float], count: int,
                      per_node_cap: int,
                      node_filter=None,
                      backlog: "int | None" = None,
                      fill_extra: "int | None" = None,
                      max_nodes: "int | None" = None) -> list:
        """ONE ledger lock pass allocates up to ``count`` same-demand
        tasks across the alive nodes — the sharded dispatch lanes'
        replacement for per-task ``try_acquire`` calls. Returns
        ``[(node, k, k_overcommitted), ...]``.

        Each node takes its free slots (bounded by ``per_node_cap``)
        plus an over-subscribed fill of ``count // n_nodes`` more
        (availability goes negative — the daemon parks the excess in
        admission, exactly like the classic batch-fill path). A node
        with ZERO free slots is never over-subscribed: tasks stay
        queued driver-side (and cancellable) instead of parking behind
        a saturated daemon."""
        plan: list = []
        with self._lock:
            n_all = sum(1 for n in self._nodes.values() if n.alive)
            nodes = [n for n in self._nodes.values()
                     if n.alive and (node_filter is None
                                     or node_filter(n))]
            if not nodes:
                return plan
            # Same fill pacing as the classic batch path: the
            # over-subscription budget divides the backlog across ALL
            # alive nodes, so a deep queue ships full batches while a
            # modest burst leaves a cancellable driver-side tail.
            # ``backlog`` is the caller's WHOLE queued population (a
            # lane's groups beyond this allocation's count); a caller
            # that KNOWS it is in a sustained burst passes
            # ``fill_extra`` outright (the lanes' accumulation linger
            # quantizes bursts into full-depth allocations).
            if fill_extra is None:
                fill_extra = min(
                    per_node_cap,
                    max(count, backlog or 0) // max(1, n_all))
            else:
                fill_extra = min(per_node_cap, fill_extra)
            nodes.sort(key=lambda n: (n.utilization(),
                                      n.node_id.hex()))
            if LOCALITY_ON and len(nodes) > 1:
                # Load-aware refinement, same policy as _pick_scored:
                # a fresh stats feed showing the classic first choice
                # measurably more loaded (>= _SPILL_MARGIN) — or gone
                # stale while an alternative reports fresh — promotes
                # the idler node to the front of the fill order.
                now = time.monotonic()
                try:
                    stale_s = float(GLOBAL_CONFIG.sched_stats_stale_s)
                except Exception:  # noqa: BLE001 — config teardown
                    stale_s = 6.0

                def load(n: NodeState) -> "float | None":
                    if n.stats_at <= 0.0 or now - n.stats_at > stale_s:
                        return None
                    return (n.stats_running + n.stats_depth
                            + n.stats_wait_s)

                loads = {id(n): load(n) for n in nodes}
                if any(v is not None for v in loads.values()):
                    default = nodes[0]
                    chosen = min(nodes, key=lambda n: (
                        loads[id(n)] if loads[id(n)] is not None
                        else float("inf"),
                        n.utilization(), n.node_id.hex()))
                    if chosen is not default:
                        d_load = loads[id(default)]
                        c_load = loads[id(chosen)]
                        if d_load is None:
                            self.sched["stale_stats_skips"] += 1
                            nodes.remove(chosen)
                            nodes.insert(0, chosen)
                        elif c_load is not None \
                                and d_load - c_load >= _SPILL_MARGIN:
                            self.sched["load_spillbacks"] += 1
                            nodes.remove(chosen)
                            nodes.insert(0, chosen)
            remaining = count
            for node in nodes:
                if remaining <= 0:
                    break
                if max_nodes is not None and len(plan) >= max_nodes:
                    break
                if demand:
                    if not node.feasible(demand):
                        continue
                    k_free = per_node_cap
                    for key, value in demand.items():
                        if value > 0:
                            k_free = min(k_free, int(
                                (node.effective_available(key) + 1e-9)
                                / value))
                else:
                    k_free = per_node_cap
                if k_free <= 0:
                    continue
                k = min(per_node_cap, k_free + fill_extra, remaining)
                n_over = max(0, k - k_free)
                for key, value in demand.items():
                    node.available[key] = node.available.get(
                        key, 0.0) - value * k
                    node.inflight[key] = node.inflight.get(
                        key, 0.0) + value * k
                plan.append((node, k, n_over))
                remaining -= k
        return plan

    def force_acquire(self, node_id: NodeID, demand: dict[str, float]) -> None:
        """Unconditional acquire (availability may go transiently
        negative). Used when a blocked task resumes: stalling the
        resume until capacity frees can deadlock the executor, and
        pick_node's fits() check keeps negative nodes unschedulable."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                node.acquire(demand)

    def wait_for_change(self, timeout: float) -> None:
        with self._lock:
            self._lock.wait(timeout)

    def notify(self) -> None:
        with self._lock:
            self._lock.notify_all()


@dataclass(eq=False)
class _QueuedTask:
    # eq=False: tasks hash/compare by IDENTITY so the waiting set and
    # the per-dep wakeup index get O(1) membership ops. The order
    # counter is an itertools.count __next__ (GIL-atomic) — the old
    # locked counter was a per-task acquire on the submit flush path.
    spec: TaskSpec
    run: Callable[[TaskSpec, NodeState], None]
    order: int = field(default_factory=_DISPATCH_ORDER)
    unresolved_deps: int = 0
    # Lifecycle flags (mutated under the dispatcher lock). Cancelled and
    # claimed entries are purged LAZILY at the next dispatch pass: a
    # 100k-deep queue makes every eager list.remove an O(queue) scan,
    # turning drains and mass-cancels into O(queue x ops).
    claimed: bool = False
    cancelled: bool = False


class Dispatcher:
    """Dependency-aware, resource-admitting task dispatcher.

    Reference roles combined: DependencyManager
    (src/ray/raylet/dependency_manager.h) gating on args, ClusterTaskManager
    (scheduling/cluster_task_manager.h:42) queue + node pick, WorkerPool
    lease grant (one thread per admitted task).
    """

    def __init__(self, cluster: ClusterState, store, on_task_state=None):
        import collections

        self._collections = collections
        self._cluster = cluster
        self._store = store
        self._lock = lock_witness.Condition(
            "scheduler.Dispatcher", plain_lock=True)
        # Dep-gated tasks, indexed BY DEPENDENCY ID: a seal group
        # touches only its dependents (O(deps sealed)), never the whole
        # waiting population — with 100k buffered submits parked in
        # _waiting, the old per-seal full rescan was O(seals x waiting).
        self._waiting: set[_QueuedTask] = set()
        self._dep_index: dict = {}  # dep ObjectID -> set[_QueuedTask]
        # True while the dispatch loop is parked in a cond-wait; wakeups
        # (submission, seals) only notify then — an active dispatch pass
        # re-checks _have_ready() itself, so notifying it is pure
        # syscall/contention overhead at high submit rates.
        self._parked = False
        # Ready tasks grouped BY ADMISSION SIGNATURE (resources +
        # strategy): one admission probe answers for a group's whole
        # FIFO, so a dispatch pass costs O(launched + groups), not
        # O(queue) — the difference between ~600/s and several
        # thousand tasks/s drained at 10k+ queue depths. Spillback
        # tasks (per-task avoid sets) go to _ready_odd and are probed
        # individually.
        self._ready_groups: "dict[tuple, collections.deque]" = {}
        self._ready_odd: list[_QueuedTask] = []
        # Live (unclaimed, uncancelled) ready-task count, maintained
        # INCREMENTALLY: enqueue +1, claim/ready-cancel -1. The O(ready)
        # _ready_tasks scan under the lock, run per pending_count/
        # wait_idle call at 100k queue depths, starved submission.
        self._num_ready_live = 0
        # return-object id -> queued task, for O(1) cancel at any queue
        # depth; entries leave at claim (running tasks are not
        # cancellable) or at cancel.
        self._by_return_id: dict = {}
        self._shutdown = False
        self._infeasible_warned: set[str] = set()
        self._on_task_state = on_task_state
        self._num_running = 0
        # Deadline-armed queued tasks, ordered by expiry: the dispatch
        # loop pops expired heads each pass (O(log n) per armed task,
        # free when no task carries a deadline) and hands them to the
        # owner's hook instead of scanning the whole queue.
        self._deadline_heap: list = []  # (deadline, order, task)
        # LIVE (unclaimed, uncancelled) deadline-armed queued tasks.
        # The heap itself only shrinks when expiry times arrive, so a
        # burst of deadline-armed tasks that all COMPLETED would
        # otherwise leave zombie entries making every later dispatch
        # pass pay the sweep; at zero live entries the sweep is
        # skipped outright and the zombie heap dropped wholesale.
        self._deadline_armed = 0
        self._on_deadline = None
        self.deadline_expired = 0
        # Sweep passes that actually ran (the zero-armed fast path
        # skips them — unit-tested in test_sharded_dispatch.py).
        self.deadline_sweeps = 0
        # Batched remote dispatch (set_batch_hooks): tasks claimed for
        # the same batch key within one pass coalesce into one runner.
        self._batch_key = None
        self._run_batch = None
        # Locality hook (set_locality_hook): spec -> {node hex ->
        # resident bytes of its large args}, consulted per admission
        # while LOCALITY_ON.
        self._locality_hook = None
        self.batches_launched = 0
        self.batch_tasks_launched = 0
        self.singles_launched = 0
        # Claims over-subscribed past a node's free slots into an open
        # batch (force-acquired; the daemon queues them in admission):
        # without this, batches were capped at the per-node free slot
        # count (~4 tasks/RPC) regardless of dispatch_batch_max.
        self.batch_overcommit = 0
        # Persistent batch-runner threads (LIFO-recycled): a 100k-task
        # drain launches thousands of batches — steady state must not
        # pay a thread spawn per batch. Singles keep the A/B-measured
        # thread-per-task launch (see _launch).
        from ray_tpu._private.rpc import _ThreadRecycler

        self._batch_runners = _ThreadRecycler("ray_tpu-task-batch",
                                              idle_s=30.0)
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name="ray_tpu-dispatcher", daemon=True)
        self._dispatch_thread.start()
        if hasattr(store, "add_batch_seal_listener"):
            # Coalesced seals (grouped batch completions) cost ONE
            # _waiting scan per group instead of one per object.
            store.add_batch_seal_listener(self._on_objects_sealed)
        else:
            store.add_seal_listener(self._on_object_sealed)

    @staticmethod
    def _sig(spec: TaskSpec) -> tuple:
        strategy = spec.scheduling_strategy
        return (tuple(sorted(spec.resources.items())),
                strategy.kind if strategy is not None else "DEFAULT",
                getattr(strategy, "node_id", None),
                getattr(strategy, "soft", False))

    def set_deadline_hook(self, on_deadline) -> None:
        """``on_deadline(spec, stage)`` seals a task whose end-to-end
        deadline expired while queued (stage "queued") or at the claim
        (stage "dispatch") — the dispatcher only cancels bookkeeping;
        the owner seals the typed TaskTimeoutError."""
        self._on_deadline = on_deadline

    def set_batch_hooks(self, batch_key, run_batch) -> None:
        """Enable batched dispatch: ``batch_key(spec, node, run)``
        returns a coalescing key (same key within one pass -> one
        batch) or None for the classic thread-per-task launch;
        ``run_batch(specs, node, complete)`` executes a batch and calls
        ``complete(spec)`` as each task finishes."""
        self._batch_key = batch_key
        self._run_batch = run_batch

    def set_locality_hook(self, hook) -> None:
        """``hook(spec)`` returns {node hex -> resident bytes of the
        spec's large args} (or a falsy value) — the byte-weighted
        locality input pick_node scores while LOCALITY_ON."""
        self._locality_hook = hook

    def _locality(self, spec: TaskSpec) -> dict | None:
        if not LOCALITY_ON:
            return None
        hook = self._locality_hook
        if hook is None:
            return None
        try:
            return hook(spec) or None
        except Exception:  # noqa: BLE001 — never wedge dispatch
            return None

    def _enqueue_ready_locked(self, task: _QueuedTask) -> None:
        # Caller holds self._lock.
        self._num_ready_live += 1
        if getattr(task.spec, "_avoid_nodes", None):
            self._ready_odd.append(task)
            return
        self._ready_groups.setdefault(
            self._sig(task.spec),
            self._collections.deque()).append(task)

    def _have_ready(self) -> bool:
        # Caller holds self._lock.
        return bool(self._ready_odd) or any(
            self._ready_groups.values())

    # ------------------------------------------------------------ submission

    def submit(self, spec: TaskSpec, run: Callable[[TaskSpec, NodeState], None],
               deps: list) -> None:
        self.submit_many(((spec, run, deps),))

    def submit_many(self, items) -> None:
        """Enqueue a whole submit flush under ONE lock acquire with at
        most one wakeup: ``items`` is an iterable of (spec, run, deps).
        The contains() checks must happen under self._lock:
        _on_objects_sealed also takes it, so a dep sealing concurrently
        either shows up in contains() here or finds the task already
        indexed under that dep."""
        sig_memo: dict = {}
        with self._lock:
            for spec, run, deps in items:
                task = _QueuedTask(spec=spec, run=run)
                if deps:
                    pending = {d.id() for d in deps
                               if not self._store.contains(d.id())}
                else:
                    pending = None  # dep-free: skip the set build
                task.unresolved_deps = len(pending) if pending else 0
                if task.unresolved_deps == 0:
                    if getattr(spec, "_avoid_nodes", None):
                        self._num_ready_live += 1
                        self._ready_odd.append(task)
                    else:
                        # One _sig per distinct (resources, strategy)
                        # object pair per flush: a burst from one
                        # RemoteFunction shares both, so the sorted-
                        # tuple build is paid once, not per task. id()
                        # keys are safe within this call — the specs
                        # keep the objects alive.
                        key = (id(spec.resources),
                               id(spec.scheduling_strategy))
                        sig = sig_memo.get(key)
                        if sig is None:
                            sig = sig_memo[key] = self._sig(spec)
                        self._num_ready_live += 1
                        self._ready_groups.setdefault(
                            sig, self._collections.deque()).append(task)
                else:
                    task._dep_ids = pending
                    self._waiting.add(task)
                    for dep_id in pending:
                        self._dep_index.setdefault(dep_id, set()).add(task)
                for rid in task.spec.return_ids:
                    self._by_return_id[rid] = task
                if getattr(spec, "deadline", None) is not None:
                    heapq.heappush(self._deadline_heap,
                                   (spec.deadline, task.order, task))
                    self._deadline_armed += 1
            if self._parked:
                self._lock.notify_all()

    def _on_object_sealed(self, object_id) -> None:
        self._on_objects_sealed((object_id,))

    def _on_objects_sealed(self, object_ids) -> None:
        with self._lock:
            if not self._dep_index:
                return  # nothing dep-gated: seal groups cost O(1)
            woke = False
            for object_id in object_ids:
                dependents = self._dep_index.pop(object_id, None)
                if not dependents:
                    continue
                for task in dependents:
                    if task.cancelled:
                        continue
                    dep_ids = task._dep_ids
                    dep_ids.discard(object_id)
                    task.unresolved_deps = len(dep_ids)
                    if task.unresolved_deps == 0:
                        self._waiting.discard(task)
                        self._enqueue_ready_locked(task)
                        woke = True
            if woke and self._parked:
                self._lock.notify_all()

    # -------------------------------------------------------------- dispatch

    def _expire_deadlines(self) -> None:
        """Cancel queued tasks whose deadline passed (mid-queue expiry
        rides the same lazy-purge cancel machinery as user cancels) and
        hand their specs to the deadline hook for sealing."""
        if not self._deadline_heap:
            return
        now = time.time()
        expired: list = []
        with self._lock:
            if self._deadline_armed <= 0:
                # Zero live deadline-armed tasks: skip the sweep and
                # drop the zombie entries (claimed/cancelled tasks
                # whose expiry times haven't arrived) wholesale —
                # deadline-free workloads pay nothing here.
                self._deadline_heap.clear()
                return
            self.deadline_sweeps += 1
            while self._deadline_heap and self._deadline_heap[0][0] <= now:
                _, _, task = heapq.heappop(self._deadline_heap)
                if task.claimed or task.cancelled:
                    continue  # ran (or was cancelled) in time
                task.cancelled = True
                self.deadline_expired += 1
                self._deadline_armed -= 1
                for rid in task.spec.return_ids:
                    self._by_return_id.pop(rid, None)
                if not task.unresolved_deps:
                    self._num_ready_live -= 1
                else:
                    self._drop_waiting(task)
                expired.append(task.spec)
        hook = self._on_deadline
        for spec in expired:
            if hook is not None:
                hook(spec, "queued")

    def _dispatch_loop(self) -> None:
        while True:
            with self._lock:
                while not self._have_ready() and not self._shutdown:
                    self._parked = True
                    try:
                        self._lock.wait(timeout=0.2)
                    finally:
                        self._parked = False
                    if self._deadline_armed:
                        break  # sweep expiries even while idle-parked
                if self._shutdown:
                    return
            self._expire_deadlines()
            # Tasks claimed for the same batch key (one remote node)
            # within this pass coalesce; _flush_batches launches them
            # as single execute_task_batch runners.
            batches: dict = {}
            launched_any = bool(self._drain_groups(batches))
            launched_any |= bool(self._drain_odd(batches))
            self._flush_batches(batches)
            if not launched_any:
                # Nothing admitted: wait for resources to free up.
                self._cluster.wait_for_change(0.05)

    def _pop_next(self, dq) -> "_QueuedTask | None":
        """Next live task at a group's head (zombies purged in
        passing); None when the group is exhausted. Only the dispatch
        thread pops, so the head is stable across the admission probe."""
        with self._lock:
            while dq:
                task = dq[0]
                if task.claimed or task.cancelled:
                    dq.popleft()
                    continue
                return task
        return None

    def _claim(self, task: _QueuedTask, node: NodeState) -> bool:
        expired = False
        with self._lock:
            if task.cancelled:
                # Concurrently cancelled after admission: give the
                # acquired resources back or the node leaks them.
                self._cluster.release(node.node_id, task.spec.resources)
                return False
            deadline = getattr(task.spec, "deadline", None)
            if deadline is not None and time.time() > deadline:
                # Budget died between enqueue and claim: never launch
                # dead work — release the admission; the hook seals the
                # typed error outside the lock.
                task.cancelled = True
                expired = True
                self.deadline_expired += 1
                self._deadline_armed -= 1
                self._num_ready_live -= 1
                for rid in task.spec.return_ids:
                    self._by_return_id.pop(rid, None)
                self._cluster.release(node.node_id, task.spec.resources)
            else:
                task.claimed = True
                if deadline is not None:
                    self._deadline_armed -= 1
                self._num_ready_live -= 1
                self._num_running += 1
                if tracing.TRACE_ON or perf.PERF_ON:
                    # Dispatch-claim stage stamp: the run callable's
                    # owner (worker.py) folds it into the task's
                    # stage_ts map (tracing) and the perf plane's
                    # dispatch→rpc histogram anchors on it (always-on).
                    task.spec._stage_dispatch = time.time()
                # Running tasks are past cancellation: drop the cancel
                # index so a late cancel() can't race the real result
                # with a TaskCancelledError.
                for rid in task.spec.return_ids:
                    self._by_return_id.pop(rid, None)
        if expired:
            hook = self._on_deadline
            if hook is not None:
                hook(task.spec, "dispatch")
            return False
        if perf.PERF_ON:
            # submit→dispatch hop, measured entirely on the driver
            # clock (outside the scheduler lock — the histogram has
            # its own short lock).
            sub = getattr(task.spec, "_submit_ts", None)
            claim = getattr(task.spec, "_stage_dispatch", None)
            if sub is not None and claim is not None:
                perf.record_stage("submit_dispatch",
                                  max(0.0, claim - sub))
        return True

    def _drain_groups(self, batches: dict | None = None) -> int:
        """One pass over the signature groups: each group launches from
        its FIFO head until admission fails for that signature — the
        other queued thousands with the same demand are never touched."""
        launched = 0
        with self._lock:
            groups = [(sig, dq) for sig, dq
                      in self._ready_groups.items() if dq]
            # Drop exhausted groups so long-lived drivers don't
            # accumulate dead signature keys.
            for sig in [s for s, dq in self._ready_groups.items()
                        if not dq]:
                del self._ready_groups[sig]
        for sig, dq in groups:
            sticky: NodeState | None = None
            # Batch-fill over-subscription: once a remote batch to the
            # sticky node is open, keep claiming into it PAST the
            # node's free slots (force-acquired; the daemon queues the
            # excess in admission) until the fill budget runs out, then
            # rotate to the next node via pick_node. Without this,
            # batch depth was capped at the per-node free slot count
            # (~4 tasks/RPC) however large dispatch_batch_max is — and
            # on a many-node box each shallow batch pays a full daemon
            # wake. The budget adapts to the backlog: a deep queue
            # fills whole batches (amortization wins), a small burst
            # keeps the classic spread (a handful of long tasks must
            # not pile onto one node while others idle).
            staged_remote = False
            fill_left = 0
            fill_budget = 0
            if batches is not None and self._run_batch is not None:
                with self._lock:
                    backlog = len(dq)
                n_nodes = max(1, len(self._cluster.nodes()))
                fill_budget = min(self._batch_max(),
                                  max(0, backlog // n_nodes))
            while True:
                task = self._pop_next(dq)
                if task is None:
                    break
                # Sticky fast path: a run of same-signature tasks
                # re-acquires the last admitted node with one ledger op
                # while it still fits, instead of a full O(nodes)
                # pick_node scan per task (the dominant dispatch cost
                # at 100k-submit bursts). Falls back to the policy scan
                # the moment the node rejects; DEFAULT-policy intent is
                # preserved (hybrid packs below the spread threshold —
                # reference: hybrid_scheduling_policy.cc). The sticky
                # shortcut is only taken when it doesn't LOSE locality
                # bytes: a task whose large args sit elsewhere pays
                # the full scored scan instead.
                node = None
                strategy = task.spec.scheduling_strategy
                hints = self._locality(task.spec)
                if sticky is not None and (
                        strategy is None or strategy.kind == "DEFAULT"):
                    take_sticky = True
                    best = 0.0
                    if hints:
                        best = max(hints.values())
                        if float(hints.get(sticky.node_id.hex(), 0.0)) \
                                < best:
                            take_sticky = False
                    if take_sticky and self._cluster.try_acquire(
                            sticky.node_id, task.spec.resources):
                        node = sticky
                        if best > 0.0:
                            # The shortcut re-confirmed the max-bytes
                            # holder: that IS a locality placement.
                            self._cluster.record_locality_hit(best)
                overcommitted = False
                if node is None and sticky is not None \
                        and staged_remote and fill_left > 0 \
                        and (strategy is None
                             or strategy.kind == "DEFAULT"):
                    # Fill the open batch: take the sticky node's real
                    # capacity when it still fits, else force-acquire
                    # past it (availability goes negative, so pick_node
                    # skips the node for OTHER work until completions
                    # release — the ledger stays symmetric).
                    if self._cluster.try_acquire(sticky.node_id,
                                                 task.spec.resources):
                        node = sticky
                    else:
                        self._cluster.force_acquire(
                            sticky.node_id, task.spec.resources)
                        node = sticky
                        overcommitted = True
                        self.batch_overcommit += 1
                    fill_left -= 1
                if node is None:
                    node = self._try_admit(task, hints)
                    if node is None:
                        break  # signature saturated for this pass
                    # Fresh sticky: open a new fill cycle for it.
                    sticky = node
                    staged_remote = False
                    fill_left = fill_budget
                with self._lock:
                    if dq and dq[0] is task:
                        dq.popleft()
                if not self._claim(task, node):
                    continue
                task.spec._overcommit = overcommitted
                staged = None if batches is None else \
                    self._stage_batch(batches, task, node)
                if staged is None:
                    self._launch(task, node)
                elif not overcommitted:
                    staged_remote = True
                launched += 1
        return launched

    def _drain_odd(self, batches: dict | None = None) -> int:
        """Spillback tasks carry per-task avoid sets: their admission
        failures don't generalize, so they are probed individually
        (the set is small — bounded by in-flight spillbacks)."""
        with self._lock:
            if not self._ready_odd:
                return 0
            self._ready_odd = [t for t in self._ready_odd
                               if not (t.claimed or t.cancelled)]
            pending = sorted(self._ready_odd, key=lambda t: t.order)
        launched = 0
        for task in pending:
            if task.claimed or task.cancelled:
                continue
            node = self._try_admit(task, self._locality(task.spec))
            if node is None:
                continue
            if not self._claim(task, node):
                continue
            # A spillback re-claim must not carry a stale overcommit
            # mark from an earlier over-subscribed claim.
            task.spec._overcommit = False
            with self._lock:
                try:
                    self._ready_odd.remove(task)
                except ValueError:
                    pass
            if batches is None or self._stage_batch(
                    batches, task, node) is None:
                self._launch(task, node)
            launched += 1
        return launched

    @staticmethod
    def _batch_max() -> int:
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG

            return max(1, int(GLOBAL_CONFIG.dispatch_batch_max))
        except Exception:  # noqa: BLE001 — config gone mid-teardown
            return 32

    def _stage_batch(self, batches: dict, task: _QueuedTask,
                     node: NodeState):
        """Coalesce a claimed task into this pass's batch for its key
        (one execute_task_batch runner per key). Returns the batch key,
        or None when the task must take the classic thread-per-task
        launch (no hooks, local node, custom run callable, ...)."""
        key_fn = self._batch_key
        if key_fn is None:
            return None
        try:
            key = key_fn(task.spec, node, task.run)
        except Exception:  # noqa: BLE001 — never wedge dispatch
            key = None
        if key is None:
            return None
        entry = batches.get(key)
        if entry is None:
            entry = batches[key] = (node, [])
        entry[1].append(task)
        if len(entry[1]) >= self._batch_max():
            del batches[key]
            self._launch_batch(entry[1], entry[0])
        return key

    def _flush_batches(self, batches: dict) -> None:
        for node, tasks in batches.values():
            if len(tasks) == 1:
                # A batch of one gains nothing over the measured
                # thread-per-task single path.
                self._launch(tasks[0], node)
            else:
                self._launch_batch(tasks, node)
        batches.clear()

    def _launch_batch(self, tasks: "list[_QueuedTask]",
                      node: NodeState) -> None:
        """One runner thread drives a whole batch; each task's
        resources release individually as its completion streams back
        (no barrier on the slowest sibling)."""
        run_batch = self._run_batch
        by_spec = {id(t.spec): t for t in tasks}
        done_lock = lock_witness.Lock("scheduler.Dispatcher.launch_done")
        self.batches_launched += 1
        self.batch_tasks_launched += len(tasks)

        def complete(spec) -> None:
            with done_lock:
                task = by_spec.pop(id(spec), None)
            if task is None:
                return  # double-complete guard
            self._cluster.release(node.node_id, task.spec.resources)
            with self._lock:
                self._num_running -= 1
                if self._parked:
                    # wait_idle() pollers re-check on their own 0.1s
                    # beat; only a parked dispatch loop needs the kick.
                    self._lock.notify_all()

        def complete_many(specs) -> None:
            """Group completion: one ledger pass + one wakeup for a
            whole streamed result group (fused runs seal 64 at a
            time — two lock acquires per TASK was a measurable slice
            of the drain budget)."""
            with done_lock:
                tasks_done = [t for t in (by_spec.pop(id(s), None)
                                          for s in specs)
                              if t is not None]
            if not tasks_done:
                return
            self._cluster.release_many(
                node.node_id, [t.spec.resources for t in tasks_done])
            with self._lock:
                self._num_running -= len(tasks_done)
                if self._parked:
                    self._lock.notify_all()

        complete.many = complete_many

        def runner() -> None:
            try:
                run_batch([t.spec for t in tasks], node, complete)
            finally:
                # A runner that died (or under-reported) must not leak
                # admissions: complete whatever it left behind.
                with done_lock:
                    leftover = [t.spec for t in by_spec.values()]
                for spec in leftover:
                    complete(spec)

        self._batch_runners.submit(runner)

    def _try_admit(self, task: _QueuedTask,
                   locality: dict | None = None) -> NodeState | None:
        spec = task.spec
        node = self._cluster.pick_node(
            spec.resources, spec.scheduling_strategy,
            exclude=getattr(spec, "_avoid_nodes", None) or None,
            locality=locality)
        if node is None:
            if not self._cluster.is_feasible(spec.resources) \
                    and spec.name not in self._infeasible_warned:
                self._infeasible_warned.add(spec.name)
                import logging

                logging.getLogger("ray_tpu").warning(
                    "Task %s demands %s which no node can ever satisfy; "
                    "it will hang until matching nodes join.",
                    spec.name, spec.resources)
            return None
        if not self._cluster.try_acquire(node.node_id, spec.resources):
            return None
        return node

    def _launch(self, task: _QueuedTask, node: NodeState) -> None:
        def runner():
            try:
                task.run(task.spec, node)
            finally:
                self._cluster.release(node.node_id, task.spec.resources)
                with self._lock:
                    self._num_running -= 1
                    if self._parked:
                        self._lock.notify_all()

        self.singles_launched += 1
        # Thread-per-task, deliberately (for local dispatch and
        # un-batchable remote tasks): a recycled/queued runner pool was
        # A/B-measured
        # SLOWER for burst dispatch on this class of host —
        # Thread.start() blocks until the child runs, which hands the
        # GIL straight to the task; a queue handoff returns instantly
        # and lets the dispatch scan starve the runners (re-measured
        # with the pipelined RPC client: same result, the dispatch
        # pass's O(ready) scans under the lock starve submission).
        thread = threading.Thread(
            target=runner, name=f"ray_tpu-task-{task.spec.name}", daemon=True)
        thread.start()

    # --------------------------------------------------------------- control

    def _ready_tasks(self) -> list:
        # Caller holds the lock.
        out = list(self._ready_odd)
        for dq in self._ready_groups.values():
            out.extend(dq)
        return out

    def _live_ready_count(self) -> int:
        # Caller holds the lock. Claimed/cancelled zombies sit in the
        # ready queues until a dispatch pass purges them (lazy
        # removal); the incrementally-maintained counter already
        # excludes them — no O(ready) scan at 100k queue depths.
        return self._num_ready_live

    def pipeline_stats(self) -> dict:
        """Dispatch-stage drain counters (batched vs single launches)."""
        with self._lock:
            return {
                "batches_launched": self.batches_launched,
                "batch_tasks_launched": self.batch_tasks_launched,
                "singles_launched": self.singles_launched,
                "batch_overcommit": self.batch_overcommit,
            }

    def pending_count(self) -> int:
        with self._lock:
            return (len(self._waiting) + self._live_ready_count()
                    + self._num_running)

    def pending_demands(self) -> list[dict[str, float]]:
        """Resource demands of queued-not-running tasks — the autoscaler's
        input (reference: scheduler_resource_reporter.cc reports demand
        to the GCS for the autoscaler)."""
        with self._lock:
            return [dict(t.spec.resources)
                    for t in self._ready_tasks() + list(self._waiting)
                    if t.spec.resources
                    and not (t.claimed or t.cancelled)]

    def wait_idle(self, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while (len(self._waiting) + self._live_ready_count()
                   + self._num_running) > 0:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._lock.wait(timeout=0.1 if remaining is None else min(remaining, 0.1))
            return True

    def cancel_by_return_id(self, object_id) -> "TaskSpec | None":
        """Cancel the not-yet-dispatched task producing ``object_id``.

        Returns the cancelled spec, or None if the task already started
        (cancellation of running threads is not possible — matches the
        best-effort semantics of the reference's non-force cancel).
        O(1) at any queue depth: the queue entry is only FLAGGED here
        and physically purged by the next dispatch pass (a mass-cancel
        of a deep backlog must not do an O(queue) list scan per call).
        """
        with self._lock:
            task = self._by_return_id.get(object_id)
            if task is None or task.claimed or task.cancelled:
                return None
            task.cancelled = True
            if getattr(task.spec, "deadline", None) is not None:
                self._deadline_armed -= 1
            for rid in task.spec.return_ids:
                self._by_return_id.pop(rid, None)
            if not task.unresolved_deps:
                # It sat in a ready queue: keep the live count honest
                # (the zombie entry is purged lazily by dispatch).
                self._num_ready_live -= 1
            else:
                self._drop_waiting(task)
            return task.spec

    def _drop_waiting(self, task: _QueuedTask) -> None:
        # Caller holds self._lock. Remove a dep-gated task from the
        # waiting set AND its dep-index entries (else a cancelled task
        # whose deps never seal would pin the index entry forever).
        self._waiting.discard(task)
        for dep_id in getattr(task, "_dep_ids", ()):
            dependents = self._dep_index.get(dep_id)
            if dependents is not None:
                dependents.discard(task)
                if not dependents:
                    del self._dep_index[dep_id]

    def reset_unsatisfiable_avoids(self, alive_ids: set) -> None:
        """A node died: spillback avoid sets computed against the old
        membership may now exclude every live candidate — clear those
        so their tasks dispatch (running on a previously-avoided node
        beats hanging; the next bounce rebuilds the set against the
        new membership). O(spillback tasks), only on node death."""
        with self._lock:
            for task in self._ready_odd:
                if task.claimed or task.cancelled:
                    continue
                avoid = getattr(task.spec, "_avoid_nodes", None)
                if avoid and avoid >= alive_ids:
                    task.spec._avoid_nodes = set()
            if self._parked:
                self._lock.notify_all()

    def fail_hard_affinity(self, node_id_hex: str) -> "list[TaskSpec]":
        """Pop every queued task HARD-pinned to a node that just died.

        A hard NODE_AFFINITY task can never reschedule off its node
        (recovery.py applies the same rule to lineage resubmission);
        leaving it queued hangs its waiters forever. Returns the
        cancelled specs — the caller seals their returns with the
        node-death error."""
        def pinned(task: _QueuedTask) -> bool:
            strategy = task.spec.scheduling_strategy
            return (strategy is not None
                    and getattr(strategy, "kind", None) == "NODE_AFFINITY"
                    and not getattr(strategy, "soft", True)
                    and getattr(strategy, "node_id", None) == node_id_hex
                    and not task.claimed and not task.cancelled)

        failed: list = []
        with self._lock:
            victims = [t for t in self._waiting if pinned(t)]
            victims += [t for t in self._ready_odd if pinned(t)]
            for dq in self._ready_groups.values():
                victims += [t for t in dq if pinned(t)]
            for task in victims:
                task.cancelled = True
                if getattr(task.spec, "deadline", None) is not None:
                    self._deadline_armed -= 1
                for rid in task.spec.return_ids:
                    self._by_return_id.pop(rid, None)
                if not task.unresolved_deps:
                    self._num_ready_live -= 1
                else:
                    self._drop_waiting(task)
                failed.append(task.spec)
        return failed

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()


class BlockedResourceContext:
    """Release this task's CPU admission while blocked in get().

    Reference behavior: a worker blocked in ray.get notifies the raylet,
    which returns its CPU to the pool and re-admits on wake.
    """

    _tls = threading.local()

    @classmethod
    def current(cls):
        return getattr(cls._tls, "ctx", None)

    def __init__(self, cluster: ClusterState, node_id: NodeID,
                 resources: dict[str, float]):
        self._cluster = cluster
        self._node_id = node_id
        # Only CPU is returned while blocked; accelerators stay held.
        self._cpu_only = {k: v for k, v in resources.items() if k == "CPU"}
        self._depth = 0
        # Cross-process nested gets block/unblock from RPC threads.
        self._depth_lock = lock_witness.Lock(
            "scheduler.BlockedResourceContext.depth")

    def __enter__(self):
        self._tls.ctx = self
        return self

    def __exit__(self, *exc):
        self._tls.ctx = None
        return False

    def block(self):
        with self._depth_lock:
            release = self._depth == 0 and bool(self._cpu_only)
            self._depth += 1
        if release:
            self._cluster.release(self._node_id, self._cpu_only)
            self._on_release()

    def unblock(self, force: bool = False):
        with self._depth_lock:
            if self._depth <= 0:
                return  # tolerate protocol-imbalanced extra unblocks
            self._depth -= 1
            reacquire = self._depth == 0 and bool(self._cpu_only)
        if not reacquire:
            return
        if force:
            # Cross-process unblock (nested pool gets): stalling the
            # RPC reply on reacquisition would time out the worker's
            # socket; transient overcommit is the lesser evil (pick_node
            # keeps negative-availability nodes unschedulable).
            self._cluster.force_acquire(self._node_id, self._cpu_only)
            self._on_reacquire()
            return
        # Reacquire; spin-wait is acceptable because release is imminent
        # by construction (we only woke because our object sealed).
        while not self._cluster.try_acquire(self._node_id, self._cpu_only):
            time.sleep(0.001)
        self._on_reacquire()

    def _on_release(self):
        """Hook for subclasses: the blocked task's CPU was just given
        back (remote tasks also release the executing daemon's
        admission here)."""

    def _on_reacquire(self):
        """Hook for subclasses: the task resumed and re-holds its CPU."""

    def drain(self):
        """Restore admission balance at task end: if the worker died (or
        timed out) while blocked, the pending release must be undone
        before the dispatcher's own release fires, else availability is
        double-counted."""
        while True:
            with self._depth_lock:
                if self._depth <= 0:
                    return
            self.unblock(force=True)


def format_traceback(exc: BaseException) -> str:
    return "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
