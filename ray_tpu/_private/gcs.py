"""Global Control Service — the head-node control plane.

TPU-native analogue of the reference GCS (reference:
src/ray/gcs/gcs_server/gcs_server.h:78 and its managers): internal KV
(gcs_kv_manager.h), named-actor registry (gcs_actor_manager.h), node table
(gcs_node_manager.h), job table, task-event store for observability
(gcs_task_manager.h), and a pubsub hub (src/ray/pubsub/publisher.h:307).

Single-node slice: tables are in-process and thread-safe; the pubsub hub
delivers callbacks synchronously on publish. The storage interface is kept
behind ``KVStore`` so a redis/file-backed implementation can slot in for
fault tolerance (reference: store_client/redis_store_client.h:33).
"""

from __future__ import annotations

import threading

from ray_tpu._private import gcs_shard, lock_witness
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu._private.ids import ActorID, JobID, NodeID, TaskID


class StaleEpochError(Exception):
    """A control-plane WRITE carried an epoch stamp from a previous
    head incarnation: the writer is a lingering old head's client or a
    daemon/driver partitioned across a head restart. The write was
    REJECTED — retryable after the caller re-syncs (re-registers /
    re-publishes under the current epoch). Carries the head's current
    epoch so the caller can converge without another round trip."""

    def __init__(self, current_epoch: int, stale_epoch: int | None = None):
        super().__init__(
            f"stale epoch {stale_epoch} (head is at epoch "
            f"{current_epoch}); re-sync and retry")
        self.current_epoch = current_epoch
        self.stale_epoch = stale_epoch

    def __reduce__(self):
        # Default Exception reduce re-calls __init__ with the formatted
        # message; this error crosses the RPC pickle boundary and must
        # round-trip its epoch payload.
        return (StaleEpochError, (self.current_epoch, self.stale_epoch))


class KVStore:
    """Namespaced key-value store (reference: gcs_kv_manager.h)."""

    def __init__(self):
        self._lock = lock_witness.Lock("gcs.KVStore")
        self._data: dict[str, dict[bytes, bytes]] = defaultdict(dict)
        # Monotonic change counter: persistence snapshots only when dirty.
        self.version = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {ns: dict(kv) for ns, kv in self._data.items()}

    def restore(self, data: dict) -> None:
        with self._lock:
            for ns, kv in data.items():
                self._data[ns].update(kv)
            self.version += 1

    def put(self, key: bytes, value: bytes, namespace: str = "default",
            overwrite: bool = True) -> bool:
        with self._lock:
            ns = self._data[namespace]
            if not overwrite and key in ns:
                return False
            ns[key] = value
            self.version += 1
            return True

    def get(self, key: bytes, namespace: str = "default") -> bytes | None:
        with self._lock:
            return self._data[namespace].get(key)

    def delete(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            existed = self._data[namespace].pop(key, None) is not None
            if existed:
                self.version += 1
            return existed

    def exists(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            return key in self._data[namespace]

    def keys(self, prefix: bytes = b"", namespace: str = "default") -> list[bytes]:
        with self._lock:
            return [k for k in self._data[namespace] if k.startswith(prefix)]


class ObjectDirectory:
    """Cluster object-location table (reference:
    ownership_based_object_directory.h): owners batch-publish which
    nodes hold copies of their primary objects. Multi-holder: a
    broadcast object accumulates every node that pulled a full copy, so
    schedulers/recovery can pick ANY holder, not just the producer.
    Entries are leased per owner — an owner that stops refreshing (its
    driver exited) is pruned wholesale."""

    def __init__(self):
        self._lock = lock_witness.Lock("gcs.ObjectDirectory")
        # owner addr -> {object hex -> {node hex, ...}}
        self._locations: dict[str, dict[str, set[str]]] = {}
        # owner addr -> {object hex -> node hex}: copies currently on
        # DISK at their holder (spill tier). A spilled holder still
        # holds the object — restore is transparent — but consumers
        # (locality scoring above all) must not credit it with
        # zero-copy residency, and node death prunes the spill mark
        # with the holder (the disk dies with the node).
        self._spilled: dict[str, dict[str, str]] = {}
        self._seen: dict[str, float] = {}
        # Persist-relevant change counter (the head's snapshot dirty
        # check) + optional WAL emit hook: every durable mutation
        # appends its op while the table lock is held, so WAL order
        # matches application order. TTL pruning deliberately bumps
        # neither — it re-derives on restore from the reset lease
        # clocks.
        self.version = 0
        self.wal_emit = None

    def _mutated(self, op) -> None:
        # Caller holds self._lock.
        self.version += 1
        if self.wal_emit is not None:
            self.wal_emit(op)

    def snapshot_state(self) -> dict:
        """Plain-data view for the head snapshot (holder sets become
        sorted lists — deterministic bytes on disk)."""
        with self._lock:
            return {
                "locations": {
                    owner: {obj: sorted(nodes)
                            for obj, nodes in table.items()}
                    for owner, table in self._locations.items()},
                "spilled": {owner: dict(table)
                            for owner, table in self._spilled.items()},
            }

    def restore_state(self, state: dict) -> None:
        """Rehydrate from a snapshot. Owner leases restart NOW: live
        owners re-publish within their keepalive period and dead
        owners' entries age out through the normal TTL prune."""
        now = time.monotonic()
        with self._lock:
            for owner, table in (state.get("locations") or {}).items():
                dst = self._locations.setdefault(owner, {})
                for obj_hex, nodes in table.items():
                    dst.setdefault(obj_hex, set()).update(nodes)
                self._seen[owner] = now
            for owner, table in (state.get("spilled") or {}).items():
                self._spilled.setdefault(owner, {}).update(table)

    def update(self, owner: str, adds: list, removes: list) -> int:
        """Apply one owner's batched deltas; an empty update is a
        keepalive refreshing the owner's lease. ``adds`` entries are
        (object_hex, node_hex) or (object_hex, [node_hex, ...])."""
        with self._lock:
            table = self._locations.setdefault(owner, {})
            spilled = self._spilled.get(owner)
            for obj_hex, nodes in adds:
                holders = table.setdefault(obj_hex, set())
                if isinstance(nodes, str):
                    holders.add(nodes)
                else:
                    holders.update(nodes)
            for obj_hex in removes:
                table.pop(obj_hex, None)
                if spilled is not None:
                    spilled.pop(obj_hex, None)
            self._seen[owner] = time.monotonic()
            if not table:
                self._locations.pop(owner, None)
            if spilled is not None and not spilled:
                self._spilled.pop(owner, None)
            if adds or removes:
                self._mutated(("dir_update", owner, list(adds),
                               list(removes)))
            return len(table)

    def mark_spilled(self, owner: str, obj_hex: str,
                     node_hex: str) -> None:
        """One holder moved its copy of ``obj_hex`` to its spill tier
        (heartbeat-piggybacked event). The node STAYS a holder —
        fetches restore transparently — but the mark makes fetch
        plans/locality spill-aware.

        ``owner`` is the DAEMON's view of the owner (the driver's
        client endpoint); location buckets are keyed by the driver's
        export address — so the mark attaches to whichever bucket
        already holds the object (one scan over the handful of live
        owners), keeping prune/update GC authoritative. The raw owner
        key is the fallback bucket for marks arriving before the
        location publish."""
        with self._lock:
            bucket = owner
            for loc_owner, table in self._locations.items():
                if obj_hex in table:
                    bucket = loc_owner
                    break
            self._spilled.setdefault(bucket, {})[obj_hex] = node_hex
            self._mutated(("dir_spill", bucket, obj_hex, node_hex))

    def clear_spilled(self, owner: str, obj_hex: str) -> None:
        """The holder restored its copy into memory: the node is a
        full in-memory holder again (this IS the re-registration —
        spilling never removed it from the holder set)."""
        with self._lock:
            for bucket in [b for b, spilled in self._spilled.items()
                           if obj_hex in spilled]:
                spilled = self._spilled[bucket]
                spilled.pop(obj_hex, None)
                if not spilled:
                    self._spilled.pop(bucket, None)
                self._mutated(("dir_unspill", bucket, obj_hex))

    def spilled(self, owner: str | None = None) -> dict:
        """{object hex -> spilled-holder node hex}, one owner or all."""
        with self._lock:
            if owner is not None:
                return dict(self._spilled.get(owner, {}))
            out: dict[str, str] = {}
            for table in self._spilled.values():
                out.update(table)
            return out

    def locations(self, owner: str | None = None) -> dict:
        """{object hex -> sorted holder list}, for one owner or all."""
        with self._lock:
            if owner is not None:
                return {o: sorted(nodes) for o, nodes
                        in self._locations.get(owner, {}).items()}
            out: dict[str, list[str]] = {}
            for table in self._locations.values():
                for obj_hex, nodes in table.items():
                    out.setdefault(obj_hex, [])
                    out[obj_hex] = sorted(set(out[obj_hex]) | nodes)
            return out

    def prune(self, ttl_s: float = 60.0) -> None:
        now = time.monotonic()
        with self._lock:
            for owner in [o for o, seen in self._seen.items()
                          if now - seen > ttl_s]:
                self._seen.pop(owner, None)
                self._locations.pop(owner, None)
                self._spilled.pop(owner, None)
            # Fallback-bucket GC: marks that landed under a raw owner
            # key (no location publish yet) are orphans once no lease
            # tracks them and their objects appear in no bucket.
            for owner in [o for o in self._spilled
                          if o not in self._seen]:
                table = self._spilled[owner]
                for obj_hex in [
                        h for h in table
                        if not any(h in t
                                   for t in self._locations.values())]:
                    del table[obj_hex]
                if not table:
                    self._spilled.pop(owner, None)

    def prune_node(self, node_hex: str) -> list[str]:
        """A node died: remove it from every holder set so pullers and
        recovery are never handed a dead holder (reference: the object
        directory unsubscribes a dead node's locations,
        ownership_based_object_directory.h). Returns the object hexes
        that lost their LAST holder — their owners must reconstruct
        from lineage or fail waiters."""
        orphaned: list[str] = []
        with self._lock:
            for owner in list(self._locations):
                table = self._locations[owner]
                for obj_hex in list(table):
                    holders = table[obj_hex]
                    if node_hex not in holders:
                        continue
                    holders.discard(node_hex)
                    if not holders:
                        del table[obj_hex]
                        orphaned.append(obj_hex)
                if not table:
                    self._locations.pop(owner, None)
            # Spill marks die with the node: its disk tier is as gone
            # as its memory, so a spilled-only copy is a lost copy.
            for owner in list(self._spilled):
                spilled = self._spilled[owner]
                for obj_hex in [o for o, n in spilled.items()
                                if n == node_hex]:
                    del spilled[obj_hex]
                if not spilled:
                    self._spilled.pop(owner, None)
            self._mutated(("dir_prune_node", node_hex))
        return orphaned


class PubSub:
    """In-process pub/sub hub (reference: src/ray/pubsub/publisher.h:307)."""

    def __init__(self):
        self._lock = lock_witness.Lock("gcs.PubSub")
        self._subs: dict[str, list[Callable[[Any], None]]] = defaultdict(list)

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> Callable[[], None]:
        with self._lock:
            self._subs[channel].append(callback)

        def unsubscribe():
            with self._lock:
                try:
                    self._subs[channel].remove(callback)
                except ValueError:
                    pass

        return unsubscribe

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            callbacks = list(self._subs.get(channel, ()))
        for cb in callbacks:
            try:
                cb(message)
            except Exception:  # noqa: BLE001 — one bad subscriber must not starve the rest
                # Flight-recorded instead of silently eaten: a
                # subscriber raising on every publish is a real bug
                # (lost actor/node events) that used to be invisible.
                from ray_tpu._private import flight_recorder

                flight_recorder.record("pubsub.callback_error", channel)


@dataclass
class ActorRecord:
    actor_id: ActorID
    name: str | None
    namespace: str
    class_name: str
    state: str = "PENDING"  # PENDING / ALIVE / RESTARTING / DEAD
    max_restarts: int = 0
    num_restarts: int = 0
    death_cause: str | None = None
    handle: Any = None  # the live LocalActor executor (single-node slice)
    placement_hint: Any = None
    # Where the actor executes (reference: the GCS actor table records
    # the owner/executing address — gcs_actor_manager.h). Driver-hosted
    # actors record the driver's own node; "" means placement is not
    # (yet) known. pid is the executing process (the driver's for
    # thread actors).
    node_id_hex: str = ""
    pid: int | None = None
    # Per-method defaults declared via @ray_tpu.method (e.g. num_returns).
    method_meta: dict = field(default_factory=dict)
    # Default end-to-end budget (seconds) every method call of this
    # actor inherits (@remote(_deadline_s=...)); 0 = none. Per-call
    # .options(_deadline_s=...) overrides.
    default_deadline_s: float = 0.0


@dataclass
class NodeRecord:
    node_id: NodeID
    address: str
    resources: dict[str, float]
    labels: dict[str, str] = field(default_factory=dict)
    # RPC address of the node's executor service (empty for nodes that
    # cannot run tasks, e.g. pure drivers).
    executor_address: str = ""
    # Durable host identity (boot-id based, same_host.host_identity):
    # daemons with equal host_id share POSIX shared memory and take the
    # same-host zero-copy fetch path instead of chunked RPC pulls.
    host_id: str = ""
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    # Live usage piggybacked on heartbeats (reference: ray_syncer's
    # resource-usage broadcast; here the heartbeat IS the sync channel).
    available: dict[str, float] = field(default_factory=dict)


@dataclass
class JobRecord:
    job_id: JobID
    start_time: float = field(default_factory=time.time)
    end_time: float | None = None
    status: str = "RUNNING"
    entrypoint: str = ""       # submitted jobs: the shell command
    message: str = ""          # human-readable status detail
    submission_id: str = ""    # user-facing id (job submission API)


@dataclass
class TaskEvent:
    """Observability record (reference: gcs_task_manager.h task events)."""

    task_id: TaskID
    name: str
    state: str  # PENDING / RUNNING / FINISHED / FAILED
    start_time: float = 0.0
    end_time: float = 0.0
    node_id: str = ""
    error: str | None = None
    actor_id: str | None = None
    # Per-stage lifecycle timestamps (driver clock, offset-corrected for
    # remote stages): submit / dispatch / rpc_sent / admitted /
    # worker_start / exec_start / exec_end / seal. Populated only while
    # tracing is enabled (tracing_stage_timestamps); successive state
    # records for one task MERGE their maps (record_task_event).
    stage_ts: dict = field(default_factory=dict)


class TaskEventGroup:
    """Columnar TaskEvent record: ONE object for a whole submit
    flush's PENDING entries (dense index -> id range), expanded into
    per-task :class:`TaskEvent` views lazily — only when a state query
    actually touches a member. Completions accumulate as a counter
    (the completion fast path records one group-finished bump per
    reply group); members that leave the happy path (cancel, failure,
    retry) get a REAL per-task event which always wins over the
    synthesized view."""

    __slots__ = ("task_ids", "name", "finished")

    def __init__(self, task_ids: list, name: str):
        self.task_ids = task_ids
        self.name = name
        self.finished = 0

    def synthesize(self, task_id: TaskID) -> TaskEvent:
        state = "FINISHED" if self.finished >= len(self.task_ids) \
            else "PENDING"
        return TaskEvent(task_id, self.name, state)


class GlobalControlService:
    """All control-plane tables in one place."""

    def __init__(self, kv=None):
        # The HEAD's GcsServer injects the native (C++) storage engine
        # (gcs_kv_native.make_kv_store); every other construction — a
        # local driver's in-process tables, a driver connected to a
        # remote head — keeps the Python store and never pays the
        # native build.
        self.kv = kv if kv is not None else KVStore()
        self.pubsub = PubSub()
        self._lock = lock_witness.Lock("gcs.GlobalControlService")
        self._actors: dict[ActorID, ActorRecord] = {}
        self._named_actors: dict[tuple[str, str], ActorID] = {}
        self._nodes: dict[NodeID, NodeRecord] = {}
        self._jobs: dict[JobID, JobRecord] = {}
        # Per-table persist-relevant change counters (the head's
        # snapshot dirty check — heartbeat liveness refreshes bump
        # nothing, actor/node/job MUTATIONS do) + optional WAL emit
        # hook, called with the op while the table lock is held so WAL
        # order matches application order.
        self.table_versions = {"actors": 0, "nodes": 0, "jobs": 0}
        self.wal_emit = None
        self._task_events: dict[TaskID, TaskEvent] = {}
        self._task_event_limit = 100_000
        # Columnar task-event groups (TaskEventGroup): task id -> its
        # group, bulk-built per flush; members count toward the event
        # cap like per-task records.
        self._task_groups: dict[TaskID, TaskEventGroup] = {}
        self._group_event_entries = 0
        # Events silently refused at the cap used to vanish untraceably;
        # the counter surfaces as ray_tpu_task_events_dropped_total in
        # /metrics (reference: gcs_task_manager's dropped-task-attempts
        # accounting). Sharded, each task-event domain keeps its own
        # counter; the task_events_dropped property sums them.
        self._events_dropped = 0
        # Per-node executor stats pushed on heartbeats (pipeline /
        # data_plane / faults), served to drivers as labeled /metrics
        # series — the GCS-side aggregation table. Values are
        # (stats, receipt monotonic): the receipt stamp ages a wedged
        # daemon's last report out of the load-aware scheduler's view.
        self._node_stats: dict[str, tuple] = {}
        self._node_stats_lock = lock_witness.Lock(
            "gcs.GlobalControlService.node_stats")
        # Sharded hot-table domains (gcs_shard.py): armed, the
        # heartbeat-piggybacked node stats and the task-event tables
        # split across per-shard lock domains — record_node_stats and
        # event flushes land on the owning shard without a global-lock
        # pass. Disarmed (gcs_shards=1) the single-lock tables above
        # serve byte-identically and none of this is constructed.
        self._stats_shards = None
        self._task_shards = None
        if gcs_shard.SHARDS_ON:
            n = gcs_shard.shard_count()
            self._stats_shards = [gcs_shard.NodeStatsShard(i)
                                  for i in range(n)]
            per_limit = max(1, self._task_event_limit // n)
            self._task_shards = [gcs_shard.TaskEventShard(i, per_limit)
                                 for i in range(n)]

    # ----------------------------------------------------------- persistence

    def _mutated(self, table: str, op) -> None:
        # Caller holds self._lock.
        self.table_versions[table] += 1
        if self.wal_emit is not None:
            self.wal_emit(op)

    @staticmethod
    def _actor_plain(record: ActorRecord) -> dict:
        """Persistable fields only: the live ``handle`` (an executor
        object) and ``placement_hint`` never cross a restart."""
        return {
            "actor_id": record.actor_id.binary(), "name": record.name,
            "namespace": record.namespace,
            "class_name": record.class_name, "state": record.state,
            "max_restarts": record.max_restarts,
            "num_restarts": record.num_restarts,
            "death_cause": record.death_cause,
            "node_id_hex": record.node_id_hex, "pid": record.pid,
            "method_meta": dict(record.method_meta),
            "default_deadline_s": record.default_deadline_s,
        }

    @staticmethod
    def _actor_from_plain(plain: dict) -> ActorRecord:
        return ActorRecord(
            actor_id=ActorID(plain["actor_id"]),
            name=plain.get("name"),
            namespace=plain.get("namespace", "default"),
            class_name=plain.get("class_name", ""),
            state=plain.get("state", "PENDING"),
            max_restarts=int(plain.get("max_restarts", 0)),
            num_restarts=int(plain.get("num_restarts", 0)),
            death_cause=plain.get("death_cause"),
            node_id_hex=plain.get("node_id_hex", ""),
            pid=plain.get("pid"),
            method_meta=dict(plain.get("method_meta") or {}),
            default_deadline_s=float(
                plain.get("default_deadline_s", 0.0)))

    @staticmethod
    def _node_plain(record: NodeRecord) -> dict:
        return {
            "node_id": record.node_id.binary(),
            "address": record.address,
            "resources": dict(record.resources),
            "labels": dict(record.labels),
            "executor_address": record.executor_address,
            "host_id": record.host_id, "alive": record.alive,
            "available": dict(record.available),
        }

    @staticmethod
    def _job_plain(record: JobRecord) -> dict:
        return {
            "job_id": record.job_id.binary(),
            "start_time": record.start_time,
            "end_time": record.end_time, "status": record.status,
            "entrypoint": record.entrypoint, "message": record.message,
            "submission_id": record.submission_id,
        }

    def control_snapshot(self) -> dict:
        """Plain-data dump of the persisted tables (KV rides its own
        ``snapshot()``; task events are observability, deliberately
        not persisted)."""
        with self._lock:
            return {
                "actors": [self._actor_plain(r)
                           for r in self._actors.values()],
                "nodes": [self._node_plain(r)
                          for r in self._nodes.values()],
                "jobs": [self._job_plain(r)
                         for r in self._jobs.values()],
            }

    def restore_control(self, state: dict) -> None:
        """Rehydrate actors/nodes/jobs from a snapshot (or replay one
        WAL upsert via apply_op). No pubsub is published during
        restore — subscribers reconnect after the server starts."""
        for plain in state.get("actors", []):
            self.apply_op(("actor", plain))
        for plain in state.get("nodes", []):
            self.apply_op(("node", plain))
        for plain in state.get("jobs", []):
            self.apply_op(("job", plain))

    def apply_op(self, op: tuple) -> None:
        """Apply one WAL record. Ops are state-bearing upserts (full
        record values, absolute counters), so re-applying a record the
        snapshot already covers is harmless — the property that makes
        replay effects-exactly-once under the snapshot/rotate race."""
        kind = op[0]
        if kind == "actor":
            plain = op[1]
            record = self._actor_from_plain(plain)
            with self._lock:
                self._actors[record.actor_id] = record
                if record.name is not None:
                    key = (record.namespace, record.name)
                    if record.state == "DEAD":
                        if self._named_actors.get(key) == record.actor_id:
                            self._named_actors.pop(key, None)
                    else:
                        self._named_actors[key] = record.actor_id
        elif kind == "node":
            plain = op[1]
            record = NodeRecord(
                node_id=NodeID(plain["node_id"]),
                address=plain.get("address", ""),
                resources=dict(plain.get("resources") or {}),
                labels=dict(plain.get("labels") or {}),
                executor_address=plain.get("executor_address", ""),
                host_id=plain.get("host_id", ""),
                alive=bool(plain.get("alive", True)),
                available=dict(plain.get("available") or {}))
            # last_heartbeat restarts NOW: restored-alive nodes get a
            # full timeout window to re-heartbeat before the monitor
            # declares them dead (their daemons may have survived the
            # head outage).
            with self._lock:
                self._nodes[record.node_id] = record
        elif kind == "job":
            plain = op[1]
            with self._lock:
                self._jobs[JobID(plain["job_id"])] = JobRecord(
                    job_id=JobID(plain["job_id"]),
                    start_time=plain.get("start_time", 0.0),
                    end_time=plain.get("end_time"),
                    status=plain.get("status", "RUNNING"),
                    entrypoint=plain.get("entrypoint", ""),
                    message=plain.get("message", ""),
                    submission_id=plain.get("submission_id", ""))

    def upsert_actor_mirror(self, plain: dict) -> bool:
        """Head-side upsert of a driver-published actor record (the
        cluster actor registry the snapshot persists). The death
        verdict FENCE lives here: once the head saw DEAD, no publish —
        from any epoch — resurrects the record to a live state
        (reference: the GCS actor table never revives a destroyed
        actor; recovery creates a NEW actor id). Returns False when
        the fence refused the transition."""
        record = self._actor_from_plain(plain)
        with self._lock:
            existing = self._actors.get(record.actor_id)
            if existing is not None and existing.state == "DEAD" \
                    and record.state != "DEAD":
                return False
            self._actors[record.actor_id] = record
            if record.name is not None:
                key = (record.namespace, record.name)
                if record.state == "DEAD":
                    if self._named_actors.get(key) == record.actor_id:
                        self._named_actors.pop(key, None)
                else:
                    self._named_actors[key] = record.actor_id
            self._mutated("actors", ("actor", self._actor_plain(record)))
        return True

    # ---------------------------------------------------------------- actors

    def register_actor(self, record: ActorRecord) -> None:
        with self._lock:
            if record.name is not None:
                key = (record.namespace, record.name)
                existing_id = self._named_actors.get(key)
                if existing_id is not None:
                    existing = self._actors.get(existing_id)
                    if existing is not None and existing.state != "DEAD":
                        raise ValueError(
                            f"Actor with name {record.name!r} already exists "
                            f"in namespace {record.namespace!r}")
                self._named_actors[key] = record.actor_id
            self._actors[record.actor_id] = record
            self._mutated("actors", ("actor", self._actor_plain(record)))
        self.pubsub.publish("actors", ("REGISTERED", record.actor_id))

    def update_actor_state(self, actor_id: ActorID, state: str,
                           death_cause: str | None = None) -> None:
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None:
                return
            record.state = state
            if death_cause is not None:
                record.death_cause = death_cause
            self._mutated("actors", ("actor", self._actor_plain(record)))
        self.pubsub.publish("actors", (state, actor_id))

    def get_actor(self, actor_id: ActorID) -> ActorRecord | None:
        with self._lock:
            return self._actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str = "default") -> ActorRecord | None:
        with self._lock:
            actor_id = self._named_actors.get((namespace, name))
            if actor_id is None:
                return None
            record = self._actors.get(actor_id)
            if record is None or record.state == "DEAD":
                return None
            return record

    def remove_actor(self, actor_id: ActorID, reason: str = "killed") -> None:
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None:
                return
            record.state = "DEAD"
            record.death_cause = reason
            if record.name is not None:
                self._named_actors.pop((record.namespace, record.name), None)
            self._mutated("actors", ("actor", self._actor_plain(record)))
        self.pubsub.publish("actors", ("DEAD", actor_id))

    def list_actors(self) -> list[ActorRecord]:
        with self._lock:
            return list(self._actors.values())

    # ----------------------------------------------------------------- nodes

    def register_node(self, record: NodeRecord) -> None:
        with self._lock:
            self._nodes[record.node_id] = record
            self._mutated("nodes", ("node", self._node_plain(record)))
        self.pubsub.publish("nodes", ("ALIVE", record.node_id))

    def get_node(self, node_id: NodeID) -> NodeRecord | None:
        with self._lock:
            return self._nodes.get(node_id)

    def mark_node_dead(self, node_id: NodeID) -> None:
        with self._lock:
            record = self._nodes.get(node_id)
            if record is not None:
                record.alive = False
                # Death verdicts are durable: a restarted head still
                # refuses the dead id at re-registration (the daemon
                # comes back as a fresh node, never a resurrection).
                self._mutated("nodes", ("node", self._node_plain(record)))
        self.pubsub.publish("nodes", ("DEAD", node_id))

    def heartbeat(self, node_id: NodeID,
                  available: dict | None = None) -> bool:
        """Refresh a node's liveness. Returns False when the node is
        unknown or already marked dead — the agent must re-register
        (reference: raylets re-register after GCS restart; a dead node
        is never resurrected in place, it gets a new node id)."""
        with self._lock:
            record = self._nodes.get(node_id)
            if record is None or not record.alive:
                return False
            record.last_heartbeat = time.monotonic()
            if available is not None:
                record.available = dict(available)
            return True

    def list_nodes(self) -> list[NodeRecord]:
        with self._lock:
            return list(self._nodes.values())

    # ------------------------------------------------------------------ jobs

    def register_job(self, record: JobRecord) -> None:
        with self._lock:
            self._jobs[record.job_id] = record
            self._mutated("jobs", ("job", self._job_plain(record)))

    def finish_job(self, job_id: JobID, status: str = "SUCCEEDED") -> None:
        with self._lock:
            record = self._jobs.get(job_id)
            if record is not None:
                record.status = status
                record.end_time = time.time()
                self._mutated("jobs", ("job", self._job_plain(record)))

    def list_jobs(self) -> list[JobRecord]:
        with self._lock:
            return list(self._jobs.values())

    # ----------------------------------------------------------- task events

    @property
    def task_events_dropped(self) -> int:
        """Events refused at the cap (sums the per-shard counters when
        the task-event table is sharded)."""
        if self._task_shards is None:
            return self._events_dropped
        return self._events_dropped + sum(
            dom.dropped for dom in self._task_shards)

    def _task_domain(self, task_id: TaskID):
        shards = self._task_shards
        return shards[gcs_shard.shard_of(task_id.hex(), len(shards))]

    @staticmethod
    def _record_one_shard(dom, event: TaskEvent) -> None:
        # Caller holds dom.lock — per-shard mirror of
        # _record_one_locked against the shard's slice of the cap.
        if len(dom.events) + dom.group_entries >= dom.limit \
                and event.task_id not in dom.events:
            dom.dropped += 1
            return
        prior = dom.events.get(event.task_id)
        if prior is not None and prior.stage_ts:
            merged = dict(prior.stage_ts)
            merged.update(event.stage_ts)
            event.stage_ts = merged
        dom.events[event.task_id] = event

    def crash_shard(self, index: int) -> None:
        """One shard domain crashed (gcs.shard_die): its volatile
        slices — node stats and task events — die with it, exactly as
        a real shard process loss would; heartbeats and the next event
        flushes repopulate them."""
        if self._stats_shards is not None:
            dom = self._stats_shards[index]
            with dom.lock:
                dom.rows.clear()
        if self._task_shards is not None:
            dom = self._task_shards[index]
            with dom.lock:
                dom.events.clear()
                dom.groups.clear()
                dom.group_entries = 0

    def _record_one_locked(self, event: TaskEvent) -> None:
        # Caller holds self._lock.
        if len(self._task_events) + self._group_event_entries \
                >= self._task_event_limit \
                and event.task_id not in self._task_events:
            self._events_dropped += 1
            return
        prior = self._task_events.get(event.task_id)
        if prior is not None and prior.stage_ts:
            # Later state records replace the event object; stage
            # stamps accumulated by earlier states (submit/dispatch)
            # must survive the replacement.
            merged = dict(prior.stage_ts)
            merged.update(event.stage_ts)
            event.stage_ts = merged
        self._task_events[event.task_id] = event

    def record_task_event(self, event: TaskEvent) -> None:
        if self._task_shards is not None:
            dom = self._task_domain(event.task_id)
            with dom.lock:
                self._record_one_shard(dom, event)
            return
        with self._lock:
            self._record_one_locked(event)

    def record_task_events(self, events: "list[TaskEvent]") -> None:
        """Coalesced state recording: one lock pass for a whole batch
        of task transitions (the pipelined execute path records a
        dispatch batch's RUNNING — and each completion group's
        FINISHED — in a single call). Sharded: one lock pass per
        OWNING shard instead."""
        if self._task_shards is not None:
            by: dict = {}
            for event in events:
                by.setdefault(self._task_domain(event.task_id),
                              []).append(event)
            for dom, batch in by.items():
                with dom.lock:
                    for event in batch:
                        self._record_one_shard(dom, event)
            return
        with self._lock:
            for event in events:
                self._record_one_locked(event)

    def record_task_event_group(self, task_ids: list,
                                name: str) -> "TaskEventGroup | None":
        """Columnar PENDING recording: one lock pass, one group object
        and one bulk rid->group insert for a whole flush — no per-task
        TaskEvent allocation (ISSUE 15). Returns the group (None when
        the cap refused it, counted like per-task drops)."""
        if self._task_shards is not None:
            group = TaskEventGroup(task_ids, name)
            by: dict = {}
            for task_id in task_ids:
                by.setdefault(self._task_domain(task_id),
                              []).append(task_id)
            refused = 0
            for dom, ids in by.items():
                with dom.lock:
                    if len(dom.events) + dom.group_entries \
                            + len(ids) > dom.limit:
                        # This shard's slice of the cap refuses ITS
                        # members; the rest of the flush still lands.
                        dom.dropped += len(ids)
                        refused += len(ids)
                        continue
                    dom.groups.update(dict.fromkeys(ids, group))
                    dom.group_entries += len(ids)
            return None if refused == len(task_ids) else group
        with self._lock:
            if len(self._task_events) + self._group_event_entries \
                    + len(task_ids) > self._task_event_limit:
                self._events_dropped += len(task_ids)
                return None
            group = TaskEventGroup(task_ids, name)
            self._task_groups.update(dict.fromkeys(task_ids, group))
            self._group_event_entries += len(task_ids)
            return group

    def record_task_group_finished(self, group: "TaskEventGroup",
                                   n: int) -> None:
        """Completion fast path: one counter bump per sealed reply
        group instead of a FINISHED TaskEvent per task. Sharded, the
        bump lands under the group's HOME shard (its first member's
        domain) — one stable lock, no cross-shard pass."""
        if self._task_shards is not None:
            dom = self._task_domain(group.task_ids[0])
            with dom.lock:
                group.finished += n
            return
        with self._lock:
            group.finished += n

    def merge_stage_ts(self, task_id: TaskID, stages: dict) -> None:
        """Fold late-arriving stage stamps (a reply's offset-corrected
        remote timestamps, the seal time) into an existing event."""
        if not stages:
            return
        if self._task_shards is not None:
            dom = self._task_domain(task_id)
            with dom.lock:
                event = dom.events.get(task_id)
                if event is not None:
                    event.stage_ts.update(stages)
            return
        with self._lock:
            event = self._task_events.get(task_id)
            if event is not None:
                event.stage_ts.update(stages)

    # ----------------------------------------------------- node stats

    def record_node_stats(self, node_hex: str, stats: dict) -> None:
        """Heartbeat piggyback: one node's executor stats snapshot,
        stamped with the RECEIPT time — a wedged daemon that stops
        heartbeating (but isn't declared dead yet) keeps aging here,
        so ``node_stats()`` consumers (the load-aware scheduler above
        all) can decay its last report out of their scores instead of
        treating the frozen snapshot as a live idle signal."""
        if self._stats_shards is not None:
            dom = self._stats_domain(node_hex)
            with dom.lock:
                dom.rows[node_hex] = (stats, time.monotonic())
            return
        with self._node_stats_lock:
            self._node_stats[node_hex] = (stats, time.monotonic())

    def _stats_domain(self, node_hex: str):
        shards = self._stats_shards
        return shards[gcs_shard.shard_of(node_hex, len(shards))]

    def drop_node_stats(self, node_hex: str) -> None:
        if self._stats_shards is not None:
            dom = self._stats_domain(node_hex)
            with dom.lock:
                dom.rows.pop(node_hex, None)
            return
        with self._node_stats_lock:
            self._node_stats.pop(node_hex, None)

    def node_stats(self) -> dict:
        """{node hex -> last pushed executor stats snapshot}, each
        carrying ``age_s`` — seconds since the snapshot's heartbeat
        arrived (receipt clock, monotonic). Sharded: merged across
        every stats domain."""
        now = time.monotonic()
        if self._stats_shards is not None:
            out: dict = {}
            for dom in self._stats_shards:
                with dom.lock:
                    for node_hex, (stats, at) in dom.rows.items():
                        out[node_hex] = {**stats,
                                         "age_s": round(now - at, 3)}
            return out
        with self._node_stats_lock:
            return {node_hex: {**stats, "age_s": round(now - at, 3)}
                    for node_hex, (stats, at)
                    in self._node_stats.items()}

    def cluster_stage_latency(self) -> dict:
        """Cluster-wide stage histograms: every node's heartbeat-
        shipped snapshot folded by bucket addition (exact — log-bucket
        histograms merge losslessly). {stage: merged snapshot}; node
        death pruning (drop_node_stats) removes a dead node's
        contribution on the next call."""
        from ray_tpu._private import perf_plane

        merged: dict[str, dict] = {}
        if self._stats_shards is not None:
            # Merge across shards: each domain contributes its slice
            # under its own lock, the bucket addition runs lock-free.
            tables = []
            for dom in self._stats_shards:
                with dom.lock:
                    tables.extend(
                        stats.get("stage_hist")
                        for stats, _at in dom.rows.values()
                        if isinstance(stats, dict))
        else:
            with self._node_stats_lock:
                tables = [stats.get("stage_hist")
                          for stats, _at in self._node_stats.values()
                          if isinstance(stats, dict)]
        for table in tables:
            if not isinstance(table, dict):
                continue
            for stage, snap in table.items():
                if isinstance(snap, dict):
                    perf_plane.merge_snapshots(
                        merged.setdefault(stage, {}), snap)
        return merged

    def get_task_event(self, task_id: TaskID) -> TaskEvent | None:
        if self._task_shards is not None:
            dom = self._task_domain(task_id)
            with dom.lock:
                event = dom.events.get(task_id)
                if event is not None:
                    return event
                group = dom.groups.get(task_id)
                if group is not None:
                    return group.synthesize(task_id)
                return None
        with self._lock:
            event = self._task_events.get(task_id)
            if event is not None:
                return event
            group = self._task_groups.get(task_id)
            if group is not None:
                # Lazy expansion: a real per-task record (failure,
                # cancel) would have been found above and wins.
                return group.synthesize(task_id)
            return None

    def list_task_events(self) -> list[TaskEvent]:
        if self._task_shards is not None:
            out: list[TaskEvent] = []
            for dom in self._task_shards:
                with dom.lock:
                    out.extend(dom.events.values())
                    for task_id, group in dom.groups.items():
                        if task_id not in dom.events:
                            out.append(group.synthesize(task_id))
            return out
        with self._lock:
            out = list(self._task_events.values())
            if self._task_groups:
                events = self._task_events
                for task_id, group in self._task_groups.items():
                    if task_id not in events:
                        out.append(group.synthesize(task_id))
            return out
