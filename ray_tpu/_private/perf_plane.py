"""Always-on performance plane: stage-latency histograms + per-task
resource attribution.

TPU-native analogue of the reference's always-on task metrics (the
per-stage task latencies behind ``ray summary tasks`` and the
``ray_tasks``/state-summary surfaces layered on the GCS task-events
service, gcs_task_manager.h) — the standing signal feed scheduling and
autoscaling read, as opposed to the tracing plane's armed-on-demand
timelines.

Design constraints (the reasons this can stay on by default):

- **Fixed log-bucketed histograms** (``StageHistogram``): 26 power-of-2
  buckets from 1µs to ~33s. Observing is one integer ``bit_length``
  plus two adds under a short lock — no allocation, no formatting, no
  per-task object. Snapshots are plain count lists, **mergeable by
  bucket addition**, so daemons ship them piggybacked on the existing
  heartbeat ``stats_for_sync()`` path and the GCS/driver fold them
  without losing information.
- **Durations, not timestamps**: every recorded hop is measured inside
  ONE process's clock (submit→dispatch on the driver, admission→worker
  on the daemon, the user function wall in the worker), so the plane
  needs none of the tracing plane's ClockSync machinery.
- **One module-attribute branch when disarmed** (``PERF_ON`` — the
  ``chaos.ACTIVE`` / ``tracing.TRACE_ON`` discipline), armed by the
  ``perf_plane`` config knob (default on; ``RAY_TPU_PERF_PLANE=0``
  disarms a whole cluster through the daemon child env).

Stage names (each names the hop that ENDS there; README documents the
mapping onto the stage_ts chain):

- driver:  ``submit_dispatch`` (.remote() → scheduler claim),
           ``dispatch_rpc`` (claim → execute RPC sent),
           ``rpc_seal`` (RPC sent → result sealed, the remote
           round-trip envelope), ``exec_local`` (driver-local
           in-thread/pool execution wall)
- daemon:  ``admit_worker`` (admission → worker frame pickup),
           ``exec`` (user-function wall, worker-reported)

Per-task resource attribution: workers sample ``time.thread_time`` /
``getrusage`` / peak-RSS delta around the task body and attach a
4-tuple to the reply; the owning process rolls it up per function
signature (count / cpu-seconds / wall / peak RSS). Surfaces:
``ray_tpu.util.state.summarize_tasks()`` and the
``ray_tpu_task_resources`` + ``ray_tpu_stage_latency_*`` /metrics
families.
"""

from __future__ import annotations

import resource
import threading
import time

_thread_time = time.thread_time
_wall_time = time.time
_getrusage = resource.getrusage
_RUSAGE_SELF = resource.RUSAGE_SELF

# Bucket i covers (2^(i-1) µs, 2^i µs]; the last bucket is +Inf.
N_BUCKETS = 26
BUCKET_BOUNDS = tuple(1e-6 * (1 << i) for i in range(N_BUCKETS))

# The ONE production branch: instrumentation sites across the runtime
# check this module attribute and pay nothing else while the plane is
# disarmed. Armed from config at first Runtime/daemon init.
PERF_ON: bool = True


def _bucket_index(dt_s: float) -> int:
    """Deterministic log2 bucket for a duration: bucket i holds
    durations in (2^(i-1), 2^i] microseconds (sub-µs lands in bucket
    0; overflow saturates into the +Inf bucket)."""
    if dt_s <= 0.0:
        return 0
    n = int(dt_s * 1e6)
    if n <= 1:
        return 0
    idx = (n - 1).bit_length()
    return idx if idx < N_BUCKETS else N_BUCKETS


class StageHistogram:
    """Lock-cheap fixed-bucket latency histogram.

    ``observe`` is the hot path: one bucket-index computation and three
    updates under a short lock. ``snapshot()`` returns the mergeable
    plain-data form ({"counts": [...N_BUCKETS+1 ints], "sum": s,
    "count": n}) that rides heartbeats and /metrics."""

    __slots__ = ("_counts", "_sum", "_count", "_lock")

    def __init__(self):
        self._counts = [0] * (N_BUCKETS + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, dt_s: float) -> None:
        idx = _bucket_index(dt_s)
        with self._lock:
            self._counts[idx] += 1
            self._sum += dt_s
            self._count += 1

    def observe_many(self, samples) -> None:
        """One lock pass for a whole batch of durations (the columnar
        completion path records a reply group's worth of stage hops at
        once instead of a lock acquire per task)."""
        indexed = [(_bucket_index(dt), dt) for dt in samples]
        with self._lock:
            for idx, dt in indexed:
                self._counts[idx] += 1
                self._sum += dt
            self._count += len(indexed)

    def observe_n(self, dt_s: float, n: int) -> None:
        """``n`` identical samples in one pass (a streamed reply group
        lands at one instant — every member shares the rpc_seal
        duration)."""
        idx = _bucket_index(dt_s)
        with self._lock:
            self._counts[idx] += n
            self._sum += dt_s * n
            self._count += n

    def snapshot(self) -> dict:
        with self._lock:
            return {"counts": list(self._counts), "sum": self._sum,
                    "count": self._count}


def merge_snapshots(into: dict, snap: dict) -> dict:
    """Fold one snapshot into an accumulator IN PLACE (bucket-wise
    addition — the property that makes per-node histograms cluster-
    aggregatable without approximation). Returns ``into``."""
    counts = into.setdefault("counts", [0] * (N_BUCKETS + 1))
    other = snap.get("counts") or []
    for i in range(min(len(counts), len(other))):
        counts[i] += int(other[i])
    into["sum"] = float(into.get("sum", 0.0)) + float(snap.get("sum", 0.0))
    into["count"] = int(into.get("count", 0)) + int(snap.get("count", 0))
    return into


def quantile(snap: dict, q: float) -> float:
    """Estimate a quantile from a snapshot by linear interpolation
    inside the target bucket (upper-bounded by the bucket edge). The
    +Inf bucket reports the largest finite bound."""
    counts = snap.get("counts") or []
    total = int(snap.get("count", 0))
    if total <= 0 or not counts:
        return 0.0
    target = q * total
    seen = 0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if seen + c >= target:
            hi = BUCKET_BOUNDS[i] if i < N_BUCKETS \
                else BUCKET_BOUNDS[-1]
            lo = BUCKET_BOUNDS[i - 1] if 0 < i <= N_BUCKETS else 0.0
            frac = (target - seen) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += c
    return BUCKET_BOUNDS[-1]


# --------------------------------------------------------------------------
# Process-wide stage registry
# --------------------------------------------------------------------------

_hist_lock = threading.Lock()
_hists: dict[str, StageHistogram] = {}


def record_stage(stage: str, dt_s: float) -> None:
    """Record one hop duration into this process's histogram for
    ``stage``. Callers gate on ``PERF_ON`` so the disarmed cost is one
    module-attribute branch."""
    hist = _hists.get(stage)
    if hist is None:
        with _hist_lock:
            hist = _hists.setdefault(stage, StageHistogram())
    hist.observe(dt_s)


def record_stage_many(stage: str, samples) -> None:
    """Batched record_stage: one histogram-lock pass for a whole
    group of durations."""
    if not samples:
        return
    hist = _hists.get(stage)
    if hist is None:
        with _hist_lock:
            hist = _hists.setdefault(stage, StageHistogram())
    hist.observe_many(samples)


def record_stage_n(stage: str, dt_s: float, n: int) -> None:
    """``n`` identical observations in one pass."""
    if n <= 0:
        return
    hist = _hists.get(stage)
    if hist is None:
        with _hist_lock:
            hist = _hists.setdefault(stage, StageHistogram())
    hist.observe_n(dt_s, n)


def stage_snapshot() -> dict:
    """{stage: histogram snapshot} for every stage this process has
    recorded (the heartbeat/scrape payload)."""
    with _hist_lock:
        hists = dict(_hists)
    return {stage: h.snapshot() for stage, h in hists.items()}


# --------------------------------------------------------------------------
# Per-task resource attribution
# --------------------------------------------------------------------------

_res_lock = threading.Lock()
# func signature -> [count, wall_s sum, cpu_s sum, peak rss delta kb]
_resources: dict[str, list] = {}


def sample_start() -> tuple:
    """(thread_time, wall, ru_maxrss_kb) before a task body."""
    return (_thread_time(), _wall_time(),
            _getrusage(_RUSAGE_SELF).ru_maxrss)


def sample_end(name: str, start: tuple) -> tuple:
    """Finish a sample: (name, wall_s, cpu_s, rss_delta_kb) — the
    4-tuple that rides worker replies and feeds
    ``record_task_resources``. RSS is a high-water mark, so the delta
    is how much this task RAISED the process peak (0 for tasks that
    fit under it)."""
    cpu0, wall0, rss0 = start
    return (name,
            _wall_time() - wall0,
            _thread_time() - cpu0,
            max(0, _getrusage(_RUSAGE_SELF).ru_maxrss - rss0))


def record_task_resources(name: str, wall_s: float, cpu_s: float,
                          rss_delta_kb: float, count: int = 1) -> None:
    """Fold one sample into the per-function table. ``count`` lets a
    run-level sample (fused in-daemon runs measure once around N
    tasks) keep the task count honest while the sums stay exact."""
    with _res_lock:
        row = _resources.get(name)
        if row is None:
            _resources[name] = [int(count), float(wall_s), float(cpu_s),
                                float(rss_delta_kb)]
        else:
            row[0] += int(count)
            row[1] += float(wall_s)
            row[2] += float(cpu_s)
            row[3] = max(row[3], float(rss_delta_kb))


def resource_snapshot() -> dict:
    """{func: {count, wall_s, cpu_s, peak_rss_kb}} for this process."""
    with _res_lock:
        return {name: {"count": row[0], "wall_s": row[1],
                       "cpu_s": row[2], "peak_rss_kb": row[3]}
                for name, row in _resources.items()}


def merge_resource_tables(into: dict, table: dict) -> dict:
    """Fold one per-function table into an accumulator IN PLACE
    (counts/sums add, peak RSS takes the max)."""
    for name, row in (table or {}).items():
        if not isinstance(row, dict):
            continue
        acc = into.setdefault(name, {"count": 0, "wall_s": 0.0,
                                     "cpu_s": 0.0, "peak_rss_kb": 0.0})
        acc["count"] += int(row.get("count", 0))
        acc["wall_s"] += float(row.get("wall_s", 0.0))
        acc["cpu_s"] += float(row.get("cpu_s", 0.0))
        acc["peak_rss_kb"] = max(acc["peak_rss_kb"],
                                 float(row.get("peak_rss_kb", 0.0)))
    return into


# --------------------------------------------------------------------------
# Per-function wall samples (straggler-speculation feed)
# --------------------------------------------------------------------------

# Exact recent wall-clock samples per function signature, recorded by
# the OWNER at task completion (submit -> seal on the driver's own
# clock, so every node's execution of the function lands in one merged
# sample set — the cluster view of the function's distribution). The
# speculation watcher compares in-flight elapsed walls against the p99
# of this ring; exact samples, not histogram buckets, because the
# trigger multiplies the p99 and a bucket-edge estimate would swing
# the threshold by up to 2x.
WALL_SAMPLE_CAP = 512

_wall_lock = threading.Lock()
_walls: dict[str, list] = {}  # name -> [next_idx, [samples...]]


def record_task_wall(name: str, wall_s: float) -> None:
    """One completed task's end-to-end wall (owner clock)."""
    with _wall_lock:
        entry = _walls.get(name)
        if entry is None:
            _walls[name] = [0, [float(wall_s)]]
            return
        idx, samples = entry
        if len(samples) < WALL_SAMPLE_CAP:
            samples.append(float(wall_s))
        else:
            samples[idx] = float(wall_s)
            entry[0] = (idx + 1) % WALL_SAMPLE_CAP


def wall_quantile(name: str, q: float) -> "tuple[int, float]":
    """(sample count, exact q-quantile wall) for ``name``; (0, 0.0)
    when the function has no completed samples yet."""
    with _wall_lock:
        entry = _walls.get(name)
        samples = list(entry[1]) if entry is not None else []
    if not samples:
        return 0, 0.0
    samples.sort()
    idx = min(len(samples) - 1,
              max(0, int(round(q * (len(samples) - 1)))))
    return len(samples), samples[idx]


# --------------------------------------------------------------------------
# Arm/disarm
# --------------------------------------------------------------------------


def enable() -> None:
    global PERF_ON
    PERF_ON = True


def disable() -> None:
    global PERF_ON
    PERF_ON = False


def reset() -> None:
    """Clear every histogram and the attribution table (tests; a
    shutdown/init cycle must not replay the previous session's
    latencies)."""
    with _hist_lock:
        _hists.clear()
    with _res_lock:
        _resources.clear()
    with _wall_lock:
        _walls.clear()


def init_from_config() -> None:
    """Arm/disarm from the ``perf_plane`` knob (driver init and daemon
    boot both call this; workers inherit RAY_TPU_PERF_PLANE through
    the child env at import of their config)."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    global PERF_ON
    PERF_ON = bool(GLOBAL_CONFIG.perf_plane)


# Env-driven default: forked/spawned processes (pool workers, daemons)
# arm the plane at import to match their parent without any handshake.
try:
    init_from_config()
except Exception:  # noqa: BLE001 — config unavailable mid-bootstrap
    pass
